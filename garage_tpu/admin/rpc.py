"""Admin RPC: the operator control surface of a node.

Ref parity: src/garage/admin/mod.rs:42-530 (AdminRpcHandler). The CLI
connects to any node over the normal net layer and drives cluster
management ops: status, layout staging/apply, bucket/key CRUD, worker
and stats introspection. Endpoint: "garage_tpu/admin".
"""

from __future__ import annotations

import asyncio
import logging

from ..model.helper import GarageHelper, allow_all
from ..rpc.layout.version import NodeRole
from ..utils.error import BadRequest, GarageError, NoSuchBucket

log = logging.getLogger("garage_tpu.admin")


class AdminRpcHandler:
    def __init__(self, garage):
        self.garage = garage
        self.helper = GarageHelper(garage)
        self.endpoint = garage.netapp.endpoint("garage_tpu/admin")
        self.endpoint.set_handler(self._handle)

    async def _handle(self, from_node, payload, stream):
        op = payload.get("op")
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            raise GarageError(f"unknown admin op {op!r}")
        return await fn(payload)

    # ---- cluster -------------------------------------------------------

    async def op_status(self, p):
        sys = self.garage.system
        h = sys.health()
        nodes = [
            {"id": n.id, "addr": list(n.addr) if n.addr else None,
             "is_up": n.is_up,
             "hostname": n.status.hostname if n.status else "",
             "role": self._role_of(n.id)}
            for n in sys.get_known_nodes()
        ]
        return {
            "node_id": sys.id,
            "health": {
                "status": h.status.value,
                "known_nodes": h.known_nodes,
                "connected_nodes": h.connected_nodes,
                "storage_nodes": h.storage_nodes,
                "storage_nodes_up": h.storage_nodes_up,
                "partitions_quorum": h.partitions_quorum,
            },
            "layout_version": sys.layout_manager.history.current().version,
            "nodes": nodes,
        }

    def _role_of(self, node_id):
        role = self.garage.system.layout_manager.history.current().node_role(
            node_id)
        if role is None:
            return None
        return {"zone": role.zone, "capacity": role.capacity,
                "tags": list(role.tags)}

    async def op_connect(self, p):
        addr = tuple(p["addr"])
        await self.garage.netapp.try_connect(
            addr, bytes(p["id"]) if p.get("id") else None)
        self.garage.system.peering.add_peer(
            addr, bytes(p["id"]) if p.get("id") else None)
        return {"ok": True}

    # ---- layout --------------------------------------------------------

    async def op_layout_show(self, p):
        hist = self.garage.system.layout_manager.history
        cur = hist.current()
        roles = {}
        for nid in hist.all_storage_nodes():
            r = cur.node_role(nid)
            if r:
                roles[nid.hex()] = {"zone": r.zone, "capacity": r.capacity,
                                    "tags": list(r.tags)}
        # only the actual staged DIFF — staged_roles() is the merged
        # view (current + staging) and would show every existing role
        # as "staged" forever
        staged = {
            nid.hex(): ({"zone": r.zone, "capacity": r.capacity,
                         "tags": list(r.tags)} if r else None)
            for nid, r in hist.staging.roles.items()
        }
        return {"version": cur.version, "roles": roles, "staged": staged,
                "staged_parameters": hist.staging.parameters.value}

    async def op_layout_assign(self, p):
        lm = self.garage.system.layout_manager
        role = NodeRole(zone=p.get("zone", "dc1"),
                        capacity=p.get("capacity"),
                        tags=tuple(p.get("tags", [])))
        lm.history.stage_role(bytes(p["node"]), role)
        lm.save()
        await lm.broadcast()
        return {"ok": True}

    async def op_layout_remove(self, p):
        lm = self.garage.system.layout_manager
        lm.history.stage_role(bytes(p["node"]), None)
        lm.save()
        await lm.broadcast()
        return {"ok": True}

    async def op_layout_apply(self, p):
        lm = self.garage.system.layout_manager
        # off-loop compute: an expensive assignment must not freeze a
        # node that is serving traffic mid-resize
        await lm.apply_staged_async(p.get("version"))
        return {"version": lm.history.current().version}

    async def op_layout_revert(self, p):
        """Drop all staged role/parameter changes
        (ref: cli/layout.rs cmd_revert_layout)."""
        lm = self.garage.system.layout_manager
        lm.revert_staged()  # _changed() persists + schedules broadcast
        return {"version": lm.history.current().version}

    async def op_layout_config(self, p):
        """Stage layout parameters — currently zone_redundancy
        (ref: cli/structs.rs:113-123 layout config -r)."""
        lm = self.garage.system.layout_manager
        zr = p.get("zone_redundancy")
        if zr is None:
            raise ValueError("zone_redundancy is required")
        if zr != "maximum":
            zr = int(zr)
            if zr < 1:
                raise ValueError("zone_redundancy must be >= 1 or "
                                 "'maximum'")
        lm.history.stage_parameters(zr)
        lm.save()  # staged params must survive a restart
        await lm.broadcast()
        cur = lm.history.staging.parameters.value
        return {"staged_parameters": cur}

    async def op_layout_skip_dead_nodes(self, p):
        """Advance the ack (and, with allow_missing_data, sync) trackers
        of DOWN nodes to `version`, so a permanently lost node no longer
        wedges tracker convergence and old-version GC
        (ref: cli/layout.rs cmd_layout_skip_dead_nodes,
        cli/structs.rs:182)."""
        lm = self.garage.system.layout_manager
        hist = lm.history
        version = p.get("version") or hist.current().version
        if version > hist.current().version:
            raise ValueError(f"version {version} is in the future")
        allow_missing = bool(p.get("allow_missing_data"))
        updated = []
        for node in hist.all_nongateway_nodes():
            if self.garage.system.is_up(node):
                continue
            ch = hist.update_trackers.set_max("ack", node, version)
            if allow_missing:
                ch = hist.update_trackers.set_max("sync", node,
                                                  version) or ch
                ch = hist.update_trackers.set_max("sync_ack", node,
                                                  version) or ch
            if ch:
                updated.append(node.hex())
        if updated:
            hist.cleanup_old_versions()
            lm.save()
            await lm.broadcast()
        return {"updated": updated, "version": version}

    # ---- buckets -------------------------------------------------------

    async def op_bucket_list(self, p):
        aliases = await self.helper.list_buckets()
        return {"buckets": [
            {"name": a.name, "id": a.bucket_id.hex()} for a in aliases
        ]}

    async def op_bucket_create(self, p):
        b = await self.helper.create_bucket(p["name"])
        return {"id": b.id.hex()}

    async def op_bucket_delete(self, p):
        bid = await self.helper.resolve_global_bucket_name(p["name"])
        if bid is None:
            raise NoSuchBucket(p["name"])
        await self.helper.delete_bucket(bid)
        return {"ok": True}

    async def op_bucket_info(self, p):
        bid = await self.helper.resolve_global_bucket_name(p["name"])
        if bid is None:
            raise NoSuchBucket(p["name"])
        b = await self.helper.get_existing_bucket(bid)
        counters = await self.garage.object_counter.read(
            bid, b"", list(self.garage.system.layout_manager.history
                           .all_nongateway_nodes()))
        return {
            "id": bid.hex(),
            "aliases": [a for a, v in b.params.aliases.items() if v],
            "keys": [k for k, perm in b.params.authorized_keys.items()
                     if perm.is_any],
            "objects": counters.get("objects", 0),
            "bytes": counters.get("bytes", 0),
            "unfinished_uploads": counters.get("unfinished_uploads", 0),
            "website": b.params.website_config.value,
            "quotas": b.params.quotas.value
            or {"max_size": None, "max_objects": None},
        }

    async def op_bucket_allow(self, p):
        bid = await self.helper.resolve_global_bucket_name(p["bucket"])
        if bid is None:
            raise NoSuchBucket(p["bucket"])
        key = await self.helper.get_existing_key(p["key"])
        from ..model.permission import BucketKeyPerm
        from ..utils.crdt import now_msec

        perm = key.bucket_permissions(bid)
        new = BucketKeyPerm(
            now_msec(),
            perm.allow_read or bool(p.get("read")),
            perm.allow_write or bool(p.get("write")),
            perm.allow_owner or bool(p.get("owner")),
        )
        await self.helper.set_bucket_key_permissions(bid, key.key_id, new)
        return {"ok": True}

    async def op_bucket_deny(self, p):
        bid = await self.helper.resolve_global_bucket_name(p["bucket"])
        if bid is None:
            raise NoSuchBucket(p["bucket"])
        key = await self.helper.get_existing_key(p["key"])
        from ..model.permission import BucketKeyPerm
        from ..utils.crdt import now_msec

        perm = key.bucket_permissions(bid)
        new = BucketKeyPerm(
            now_msec(),
            perm.allow_read and not p.get("read"),
            perm.allow_write and not p.get("write"),
            perm.allow_owner and not p.get("owner"),
        )
        await self.helper.set_bucket_key_permissions(bid, key.key_id, new)
        return {"ok": True}

    # ---- keys ----------------------------------------------------------

    async def op_key_new(self, p):
        k = await self.helper.create_key(p.get("name", ""))
        return {"key_id": k.key_id, "secret_key": k.params.secret_key}

    async def op_key_list(self, p):
        keys = await self.helper.list_keys()
        return {"keys": [
            {"id": k.key_id,
             "name": k.params.name.value if k.params else ""}
            for k in keys
        ]}

    async def op_key_info(self, p):
        k = await self.helper.get_existing_key(p["key"])
        return {
            "id": k.key_id,
            "name": k.params.name.value,
            "create_bucket": k.params.allow_create_bucket.value,
            "secret_key": k.params.secret_key if p.get("show_secret") else None,
            "buckets": {bid.hex(): {"read": perm.allow_read,
                                    "write": perm.allow_write,
                                    "owner": perm.allow_owner}
                        for bid, perm in k.params.authorized_buckets.items()},
        }

    async def op_key_allow(self, p):
        if p.get("create_bucket"):
            await self.helper.set_key_create_bucket(p["key"], True)
        return {"ok": True}

    async def op_key_deny(self, p):
        if p.get("create_bucket"):
            await self.helper.set_key_create_bucket(p["key"], False)
        return {"ok": True}

    async def op_key_delete(self, p):
        await self.helper.delete_key(p["key"])
        return {"ok": True}

    async def op_key_import(self, p):
        from ..model.key_table import Key

        k = Key.import_key(p["key_id"], p["secret_key"], p.get("name", ""))
        await self.garage.key_table.insert(k)
        return {"key_id": k.key_id}

    # ---- repair / block ops / snapshot (ref: garage/admin/mod.rs
    # launch repairs + block ops, garage/repair/online.rs) ---------------

    async def op_repair(self, p):
        from ..model.repair import launch_repair

        what = p.get("what", "")
        if what == "scrub":
            return await self._scrub_cmd(p.get("cmd", "start"))
        msg = launch_repair(self.garage, what)
        return {"ok": True, "msg": msg}

    async def _scrub_cmd(self, cmd: str):
        sw = getattr(self.garage.block_manager, "scrub_worker", None)
        if sw is None:
            raise BadRequest("no scrub worker on this node")
        try:
            sw.command(cmd)
        except ValueError as e:
            raise BadRequest(str(e))
        return {"ok": True, "msg": f"scrub {cmd}"}

    async def op_block_list_errors(self, p):
        res = self.garage.block_manager.resync
        # iter_errors scans the resync error tree (GL10)
        errors = await asyncio.to_thread(lambda: list(res.iter_errors()))
        return {"errors": [
            {"hash": h.hex(), "failures": count, "next_try_ms": next_ms}
            for h, count, next_ms in errors
        ]}

    async def op_block_info(self, p):
        try:
            h = bytes.fromhex(p["hash"])
        except ValueError:
            raise BadRequest(f"not a hex block hash: {p['hash']!r}")
        m = self.garage.block_manager
        state, at = m.rc.get(h)
        refs = []
        store = self.garage.block_ref_table.data
        raws = await asyncio.to_thread(store.read_range, h, None, None,
                                       100)
        for raw in raws:
            e = store.decode_stored(raw)
            refs.append({"version": e.version.hex(),
                         "deleted": e.deleted.value})
        return {
            "hash": h.hex(),
            "rc": state,
            "deletable_at": at,
            "stored_locally": (m.local_parts(h) if m.erasure
                               else m.has_local(h)),
            "refs": refs,
        }

    async def op_block_retry_now(self, p):
        res = self.garage.block_manager.resync
        try:
            hashes = [bytes.fromhex(x) for x in p.get("hashes", [])]
        except ValueError as e:
            raise BadRequest(f"bad block hash: {e}")
        n = await asyncio.to_thread(res.retry_now, hashes,
                                    bool(p.get("all")))
        return {"ok": True, "count": n}

    async def op_block_purge(self, p):
        """Tombstone every version referencing the block (cascades to
        refs + object entries; ref: admin/block.rs handle_block_purge)."""
        from ..model.s3.mpu_table import MultipartUpload
        from ..model.s3.object_table import (Object, ObjectVersion,
                                             ObjectVersionState)
        from ..model.s3.version_table import BACKLINK_OBJECT, Version

        try:
            hashes = [bytes.fromhex(x) for x in p.get("hashes", [])]
        except ValueError as e:
            raise BadRequest(f"bad block hash: {e}")
        purged_versions = 0
        purged_objects = 0
        purged_mpus = 0

        async def abort_object_version(bucket_id, key, uuid):
            kb = key.encode() if isinstance(key, str) else key
            obj = await self.garage.object_table.get(bucket_id, kb)
            if obj is None:
                return 0
            aborted = [ObjectVersion(ov.uuid, ov.timestamp,
                                     ObjectVersionState.aborted())
                       for ov in obj.versions if ov.uuid == uuid]
            if not aborted:
                return 0
            await self.garage.object_table.insert(Object(
                bucket_id, key if isinstance(key, str) else key.decode(),
                aborted))
            return 1

        for h in hashes:
            data = self.garage.block_ref_table.data
            raws = await asyncio.to_thread(data.read_range, h, None,
                                           None, 10000)
            refs = [data.decode_stored(raw) for raw in raws]
            for ref in refs:
                if ref.deleted.value:
                    continue
                v = await self.garage.version_table.get(ref.version, b"")
                if v is None:
                    continue
                if v.backlink[0] == BACKLINK_OBJECT:
                    _, bucket_id, key = v.backlink
                    purged_objects += await abort_object_version(
                        bucket_id, key, v.uuid)
                else:
                    # MPU-backed part: abort the whole upload — its
                    # object uploading-version AND the mpu row — or the
                    # client could still "complete" an upload whose data
                    # is gone (ref: admin/block.rs handle_block_purge)
                    upload_id = v.backlink[1]
                    mpu = await self.garage.mpu_table.get(upload_id, b"")
                    if mpu is not None and not mpu.deleted.value:
                        purged_objects += await abort_object_version(
                            mpu.bucket_id, mpu.key, upload_id)
                        await self.garage.mpu_table.insert(
                            MultipartUpload.new(upload_id, mpu.timestamp,
                                                mpu.bucket_id, mpu.key,
                                                deleted=True))
                        purged_mpus += 1
                await self.garage.version_table.insert(
                    Version.new(v.uuid, v.backlink, deleted=True))
                purged_versions += 1
        return {"ok": True, "versions": purged_versions,
                "objects": purged_objects, "mpus": purged_mpus}

    async def op_meta_snapshot(self, p):
        import asyncio

        from ..model.snapshot import snapshot_metadata

        path = await asyncio.to_thread(snapshot_metadata, self.garage)
        return {"ok": True, "path": path}

    async def op_worker_get(self, p):
        bv = self.garage.bg_vars
        if p.get("name"):
            try:
                return {"vars": {p["name"]: bv.get(p["name"])}}
            except KeyError:
                raise BadRequest(
                    f"unknown variable {p['name']!r}; known: "
                    f"{', '.join(sorted(bv.all()))}")
        return {"vars": bv.all()}

    async def op_worker_set(self, p):
        bv = self.garage.bg_vars
        try:
            bv.set(p["name"], p["value"])
            return {"ok": True, "value": bv.get(p["name"])}
        except KeyError:
            raise BadRequest(
                f"unknown variable {p['name']!r}; known: "
                f"{', '.join(sorted(bv.all()))}")
        except ValueError as e:
            raise BadRequest(str(e))

    # ---- workers / stats ----------------------------------------------

    async def op_worker_list(self, p):
        infos = self.garage.runner.worker_info()
        return {"workers": [
            {"id": wid, "name": i.name, "state": getattr(i, "state", ""),
             "queue": i.queue_length, "errors": i.persistent_errors,
             "tranquility": i.tranquility, "progress": i.progress}
            for wid, i in sorted(infos.items())
        ]}

    async def op_stats(self, p):
        g = self.garage
        tables = {t.name: t.data.stats() for t in g.all_tables()}
        return {
            "tables": tables,
            "block": dict(g.block_manager.metrics),
            "resync_queue": g.block_manager.resync.queue_len(),
            "resync_errors": g.block_manager.resync.errors_len(),
            "http": {},
        }
