"""Admin HTTP API: /health, /metrics (Prometheus text), /status.

Ref parity: src/api/admin/api_server.rs:232-330 + rpc/system_metrics.rs.
Bearer-token auth via admin_token/metrics_token config; /health is
always public (used by load balancers).
"""

from __future__ import annotations

import json

from ..api.http import HttpServer, Request, Response


class AdminHttpServer:
    def __init__(self, garage):
        self.garage = garage
        self.http = HttpServer(self.handle, name="admin")

    async def start(self, host: str, port: int) -> None:
        await self.http.start(host, port)

    async def stop(self) -> None:
        await self.http.stop()

    def _authorized(self, req: Request, token) -> bool:
        if token is None:
            return True
        return req.header("authorization") == f"Bearer {token}"

    async def handle(self, req: Request) -> Response:
        path = req.path
        if path == "/health":
            h = self.garage.system.health()
            status = 200 if h.status.value != "unavailable" else 503
            return Response(status, [("content-type", "text/plain")],
                            f"{h.status.value}\n".encode())
        if path == "/metrics":
            if not self._authorized(req, self.garage.config.metrics_token):
                return Response(403, [], b"forbidden")
            return Response(200,
                            [("content-type",
                              "text/plain; version=0.0.4")],
                            self.render_metrics().encode())
        if path in ("/status", "/v1/status"):
            if not self._authorized(req, self.garage.config.admin_token):
                return Response(403, [], b"forbidden")
            from .rpc import AdminRpcHandler

            h = self.garage.system.health()
            body = {
                "node": self.garage.system.id.hex(),
                "garageVersion": "garage-tpu-0.2",
                "clusterHealth": h.status.value,
                "knownNodes": h.known_nodes,
                "connectedNodes": h.connected_nodes,
                "layoutVersion":
                    self.garage.system.layout_manager.history.current().version,
            }
            return Response(200, [("content-type", "application/json")],
                            json.dumps(body).encode())
        return Response(404, [], b"not found")

    def render_metrics(self) -> str:
        """Prometheus text exposition from live counters
        (ref: rpc/system_metrics.rs, block/metrics.rs,
        table/metrics.rs)."""
        g = self.garage
        out = []

        def gauge(name, value, help_="", **labels):
            if help_:
                out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} gauge")
            lab = ",".join(f'{k}="{v}"' for k, v in labels.items())
            out.append(f"{name}{{{lab}}} {value}" if lab
                       else f"{name} {value}")

        h = g.system.health()
        gauge("cluster_healthy", 1 if h.status.value == "healthy" else 0,
              "Whether the cluster is fully healthy")
        gauge("cluster_available", 1 if h.status.value != "unavailable" else 0)
        gauge("cluster_known_nodes", h.known_nodes)
        gauge("cluster_connected_nodes", h.connected_nodes)
        gauge("cluster_storage_nodes", h.storage_nodes)
        gauge("cluster_storage_nodes_up", h.storage_nodes_up)
        gauge("cluster_partitions_quorum", h.partitions_quorum)
        gauge("cluster_layout_version",
              g.system.layout_manager.history.current().version)

        out.append("# TYPE block_manager_bytes counter")
        for k, v in g.block_manager.metrics.items():
            gauge(f"block_{k}", v)
        gauge("block_resync_queue_length",
              g.block_manager.resync.queue_len(),
              "Number of blocks in the resync queue")
        gauge("block_resync_errored_blocks",
              g.block_manager.resync.errors_len())

        for t in g.all_tables():
            s = t.data.stats()
            for k, v in s.items():
                gauge(f"table_{k}", v, table=t.name)

        for wid, info in g.runner.worker_info().items():
            gauge("worker_busy", 1 if info.state == "busy" else 0,
                  worker=info.name)
            if info.queue_length is not None:
                gauge("worker_queue_length", info.queue_length,
                      worker=info.name)
            gauge("worker_errors", info.errors, worker=info.name)
        return "\n".join(out) + "\n"
