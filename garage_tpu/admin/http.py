"""Admin HTTP API: health/metrics + the v1 cluster-management REST API.

Ref parity: src/api/admin/api_server.rs:232-330 + router_v1.rs (cluster
status/health/connect, layout staging, key + bucket CRUD, aliasing,
allow/deny) and rpc/system_metrics.rs for /metrics. Bearer-token auth
via admin_token (management) / metrics_token (/metrics); /health is
always public (used by load balancers). Management endpoints delegate
to the same AdminRpcHandler ops the CLI drives, so both operator
surfaces stay behavior-identical.
"""

from __future__ import annotations

import json

from .. import __version__
from ..api.http import HttpServer, Request, Response
from ..utils.error import BadRequest, GarageError, NoSuchBucket, NoSuchKey


def _json(body, status: int = 200) -> Response:
    return Response(status, [("content-type", "application/json")],
                    json.dumps(body, default=str).encode())


# ---- runtime-knob application ------------------------------------------
# Module-level so BOTH operator surfaces share one implementation: the
# admin HTTP routes below, and the gateway worker RPC handler
# (gateway/worker.py) that the supervisor fans /v1/s3/tuning, /v1/qos
# and /v1/chaos writes out through — runtime knobs keep working when
# the frontend is N worker processes instead of one.

def apply_s3_tuning(garage, spec: dict) -> dict:
    """Validate-then-apply the S3 data-plane knobs; returns the live
    state (the GET payload). A 400 must never leave half the update
    applied on a live node."""
    cfg = garage.config
    cache = garage.block_manager.cache
    feeder = garage.block_manager.feeder
    tier = getattr(garage.block_manager, "cache_tier", None)
    bounds = {"get_readahead_blocks": (0, 64),
              # cluster cache tier (block/cache_tier.py): runtime
              # on/off + hint breadth, so an operator can shed the
              # tier under incident pressure without a restart
              "cache_tier": (0, 1),
              "cache_tier_hint_top_n": (1, 256),
              "put_blocks_max_parallel": (1, 64),
              # hot-block read cache (block/cache.py): size + admission
              # knobs, live-resizable so bench sweeps flip the cache
              # on/off without a server restart (0 = disabled)
              "read_cache_max_bytes": (0, 1 << 40),
              "read_cache_probation_pct": (1, 90),
              # device feeder ([tpu] knobs, block/feeder.py): pipeline
              # depth and host/device routing floors, live-tunable so
              # bench sweeps walk the overlap/latency trade without a
              # server restart
              "feeder_inflight_batches": (1, 16),
              "feeder_device_min_bytes": (0, 1 << 40),
              "feeder_device_min_items": (1, 4096),
              # read-side routing floors (decode/repair — ISSUE 13)
              "feeder_device_min_decode_bytes": (0, 1 << 40),
              "feeder_device_min_decode_items": (1, 4096)}
    validated = {}
    for k, raw in spec.items():
        if k not in bounds:
            raise BadRequest(f"unknown s3 tuning knob {k!r}")
        if k.startswith("cache_tier") and tier is None:
            raise BadRequest(
                "cache tier is disabled in config "
                "([block] cache_tier = false); restart to enable")
        lo, hi = bounds[k]
        v = int(raw)
        if v < lo or v > hi:
            raise BadRequest(f"{k} must be in [{lo}, {hi}]")
        validated[k] = v
    for k, v in validated.items():
        if k == "read_cache_max_bytes":
            cfg.block_read_cache_max_bytes = v
            cache.configure(max_bytes=v)
        elif k == "read_cache_probation_pct":
            cache.configure(probation_pct=v)
        elif k == "cache_tier":
            tier.enabled = bool(v)
        elif k == "cache_tier_hint_top_n":
            tier.hint_top_n = v
        elif k.startswith("feeder_"):
            setattr(feeder, k[len("feeder_"):], v)
        else:
            setattr(cfg, "s3_" + k, v)
    return s3_tuning_state(garage)


def s3_tuning_state(garage) -> dict:
    from ..api.http import DRAIN_HIGH_WATER

    cache = garage.block_manager.cache
    feeder = garage.block_manager.feeder
    return {
        "get_readahead_blocks": garage.config.s3_get_readahead_blocks,
        "put_blocks_max_parallel":
            garage.config.s3_put_blocks_max_parallel,
        "drain_high_water": DRAIN_HIGH_WATER,
        "read_cache_max_bytes": cache.max_bytes,
        "read_cache_probation_pct": cache.probation_pct,
        "read_cache": cache.stats(),
        "cache_tier": (garage.block_manager.cache_tier.stats()
                       if getattr(garage.block_manager, "cache_tier",
                                  None) is not None
                       else {"enabled": False}),
        "feeder_inflight_batches": feeder.inflight_batches,
        "feeder_device_min_bytes": feeder.device_min_bytes,
        "feeder_device_min_items": feeder.device_min_items,
        "feeder_device_min_decode_bytes": feeder.device_min_decode_bytes,
        "feeder_device_min_decode_items": feeder.device_min_decode_items,
        "feeder_pipeline": feeder.pipeline_stats(),
    }


def apply_chaos_spec(spec: dict) -> dict:
    """Validate-then-apply a fault-injection spec against THIS
    process's chaos controller; returns its state."""
    from ..chaos import injector as chaos_inj

    ctl = chaos_inj.controller()
    allowed = {"kind", "prob", "count", "node", "peer", "endpoint",
               "hash_prefix", "delay_s", "rate_bps"}
    # validate EVERYTHING before the first mutation — a 400 must never
    # leave the live controller half-updated (cleared, reseeded, or
    # with only some faults armed)
    new_faults = []
    for f in spec.get("faults", []):
        bad = set(f) - allowed
        if bad:
            raise BadRequest(f"unknown fault field(s): {sorted(bad)}")
        if f.get("kind") not in chaos_inj.ALL_KINDS:
            raise BadRequest(
                f"unknown fault kind {f.get('kind')!r} "
                f"(kinds: {', '.join(chaos_inj.ALL_KINDS)})")
        fs = chaos_inj.FaultSpec(
            kind=f["kind"],
            prob=float(f.get("prob", 1.0)),
            count=(int(f["count"])
                   if f.get("count") is not None else None),
            node=str(f.get("node", "")),
            peer=str(f.get("peer", "")),
            endpoint=str(f.get("endpoint", "")),
            hash_prefix=str(f.get("hash_prefix", "")),
            delay_s=float(f.get("delay_s", 0.05)),
            rate_bps=float(f.get("rate_bps", 1 << 20)))
        if not 0.0 <= fs.prob <= 1.0:
            raise BadRequest("prob must be in [0, 1]")
        new_faults.append(fs)
    seed = int(spec["seed"]) if "seed" in spec else None
    if spec.get("clear"):
        ctl.clear()
    if seed is not None:
        ctl.reseed(seed)
    for fs in new_faults:
        ctl.add(fs)
    if "enabled" in spec:
        if spec["enabled"]:
            chaos_inj.arm()
        else:
            chaos_inj.disarm(clear=False)
    elif new_faults:
        chaos_inj.arm()  # arming faults implies enabling
    return ctl.state()


def relabel_metrics(text: str, worker: str) -> list[str]:
    """Stamp a `worker` label onto every sample line of a worker's
    Prometheus text exposition (HELP/TYPE lines dropped — the store's
    own render already carries them once). Merging N workers' renders
    this way is what makes per-worker series addressable
    (`api_request_duration_seconds_count{api="s3",worker="1"}`)."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_labels, _, value = line.rpartition(" ")
        if not name_labels:
            continue
        if name_labels.endswith("}"):
            out.append(f'{name_labels[:-1]},worker="{worker}"}} {value}')
        else:
            out.append(f'{name_labels}{{worker="{worker}"}} {value}')
    return out


class AdminHttpServer:
    def __init__(self, garage, admin_rpc=None):
        self.garage = garage
        self.http = HttpServer(self.handle, name="admin")
        if admin_rpc is None:
            from .rpc import AdminRpcHandler

            admin_rpc = AdminRpcHandler(garage)
        self.rpc = admin_rpc

    async def start(self, host: str, port=None) -> None:
        # a path (port None) binds a Unix-domain socket, like the
        # reference's UnixOrTCPSocketAddress bind addresses
        if port is None:
            await self.http.start_unix(host)
        else:
            await self.http.start(host, port)

    async def stop(self) -> None:
        await self.http.stop()

    def _authorized(self, req: Request, token) -> bool:
        if token is None:
            return True
        return req.header("authorization") == f"Bearer {token}"

    @staticmethod
    def _bucket_info_json(r: dict) -> dict:
        wc = r.get("website")
        return {
            "id": r["id"], "globalAliases": r["aliases"],
            "keys": r["keys"], "objects": r["objects"],
            "bytes": r["bytes"],
            "unfinishedUploads": r["unfinished_uploads"],
            "websiteAccess": wc is not None,
            "websiteConfig": ({"indexDocument": wc.get("index_document"),
                               "errorDocument": wc.get("error_document")}
                              if wc else None),
            "quotas": {"maxSize": r.get("quotas", {}).get("max_size"),
                       "maxObjects": r.get("quotas", {}).get("max_objects")},
        }

    async def handle(self, req: Request) -> Response:
        path = req.path
        if path == "/health":
            h = self.garage.system.health()
            status = 200 if h.status.value != "unavailable" else 503
            return Response(status, [("content-type", "text/plain")],
                            f"{h.status.value}\n".encode())
        if path == "/metrics":
            if not self._authorized(req, self.garage.config.metrics_token):
                return Response(403, [], b"forbidden")
            # the first table_size_bytes read scans each table for its
            # baseline — do that off the event loop ONCE; steady-state
            # scrapes read the cached base + delta inline
            import asyncio

            if any(t.data._bytes_base is None
                   for t in self.garage.all_tables()):
                await asyncio.to_thread(
                    lambda: [t.data.size_bytes()
                             for t in self.garage.all_tables()])
            # the whole render runs off-loop: per-table row counts and
            # the metadata engine_stats() are COUNT(*) scans on sqlite —
            # at millions of rows a scrape must not stall the loop
            body = await asyncio.to_thread(self.render_metrics)
            sup = getattr(self.garage, "gateway_supervisor", None)
            if sup is not None:
                # aggregate the worker processes' series under a
                # `worker` label (best-effort: a worker mid-respawn is
                # skipped, its absence shows in gateway_worker_up)
                lines = []
                for idx, res in (await sup.fanout({"op": "metrics"},
                                                  timeout=15.0)).items():
                    if isinstance(res, dict) and "text" in res:
                        lines.extend(relabel_metrics(res["text"],
                                                     str(idx)))
                body += "\n".join(lines) + ("\n" if lines else "")
            return Response(200,
                            [("content-type",
                              "text/plain; version=0.0.4")],
                            body.encode())
        if path == "/check" and req.method == "GET":
            return await self._check_domain(req)
        if path == "/v1/trace" and req.method == "GET":
            # span ring tail (admin-token gated like management routes);
            # ?limit=N caps the tail. Ref: the reference exports spans
            # via OTLP (garage/tracing_setup.rs); this surfaces the same
            # span stream without a collector.
            if self.garage.config.admin_token is None \
                    or not self._authorized(req,
                                            self.garage.config.admin_token):
                return Response(403, [], b"forbidden")
            from ..utils.tracing import tracer

            try:
                limit = int(req.query.get("limit", "200"))
            except ValueError:
                return _json({"code": "InvalidRequest",
                              "message": "limit must be an integer"}, 400)
            limit = max(1, min(limit, 2048))
            spans = list(tracer.ring)[-limit:]
            return _json({"enabled": tracer.enabled, "spans": spans}, 200)
        # management endpoints: an UNSET admin token means access is
        # always denied (the reference's admin_token semantics) —
        # /metrics above differs deliberately (open when no
        # metrics_token is configured, for scrapers)
        if self.garage.config.admin_token is None \
                or not self._authorized(req,
                                        self.garage.config.admin_token):
            return Response(403, [], b"forbidden")
        try:
            resp = await self._route_v1(req)
        except (BadRequest, NoSuchBucket, NoSuchKey, GarageError) as e:
            code = 404 if isinstance(e, (NoSuchBucket, NoSuchKey)) else 400
            return _json({"code": type(e).__name__, "message": str(e)},
                         code)
        except (KeyError, ValueError) as e:
            return _json({"code": "InvalidRequest", "message": str(e)}, 400)
        if resp is None:
            return _json({"code": "NotFound",
                          "message": f"no such endpoint {req.method} {path}"},
                         404)
        return resp

    # ---- v1 management REST (ref: router_v1.rs:97-131) -----------------

    async def _route_v1(self, req: Request):  # noqa: C901
        m = req.method
        path = req.path
        if path.startswith("/v0/"):
            path = "/v1/" + path[4:]  # v0 compat: same handlers
        q = req.query

        async def body_json():
            raw = await req.body.read_all(limit=1 << 20)
            return json.loads(raw.decode()) if raw else None

        if path == "/v1/s3/tuning":
            # S3 data-plane knobs (README "S3 data-plane tuning"):
            # runtime-readable AND writable so bench sweeps don't need a
            # server restart per setting. Writes touch plain ints read
            # fresh on every request — safe on a live node. In gateway
            # mode the write fans out to every worker process (theirs
            # are the caches/configs actually serving traffic).
            if m == "POST":
                spec = await body_json() or {}
                state = apply_s3_tuning(self.garage, spec)
                sup = getattr(self.garage, "gateway_supervisor", None)
                if sup is not None and spec:
                    state["workers"] = await sup.fanout(
                        {"op": "tuning", "spec": spec})
                return _json(state)
            elif m != "GET":
                return None
            return _json(s3_tuning_state(self.garage))

        if path == "/v1/chaos":
            # fault injection control plane (garage_tpu/chaos/): GET
            # reports armed faults + fired counts; POST arms/updates.
            # Body: {"enabled": bool, "seed": int, "clear": bool,
            #        "faults": [{kind, prob, count, node, peer,
            #                    endpoint, hash_prefix, delay_s,
            #                    rate_bps}, ...]}
            # Gateway mode: the spec ALSO fans out to every worker
            # process — net/rpc faults scoped at the API side must fire
            # in the processes actually making those calls.
            from ..chaos import injector as chaos_inj

            if m == "POST":
                spec = await body_json() or {}
                state = apply_chaos_spec(spec)
                sup = getattr(self.garage, "gateway_supervisor", None)
                if sup is not None:
                    state["workers"] = await sup.fanout(
                        {"op": "chaos", "spec": spec})
                return _json(state)
            elif m != "GET":
                return None
            return _json(chaos_inj.controller().state())

        if path == "/v1/zones" and m == "GET":
            # per-zone health rollup (garage_tpu/zones/, ISSUE 16):
            # up / degraded / partitioned per zone, derived live from
            # peering state — during a zone partition this flips within
            # one ping interval, observer-relative (each side of the
            # cut sees the OTHER side partitioned)
            return _json(self.garage.system.zone_health.snapshot())

        if path == "/v1/metadata" and m == "GET":
            # metadata-engine observability (README "Metadata at
            # scale"): per-engine internals (lsm: segments, compaction
            # backlog, WAL/memtable bytes; sqlite: file size), per-table
            # row/todo depths, compaction worker state, and the
            # resize-phase readout so one call answers "what is the
            # metadata plane doing right now"
            import asyncio as _aio

            g = self.garage

            def collect():
                # engine_stats + per-table depths are COUNT(*) scans on
                # sqlite: keep them off the event loop (GL01 in spirit)
                return (g.db.engine_stats(),
                        {t.name: t.data.stats() for t in g.all_tables()})

            engine, tables = await _aio.to_thread(collect)
            lm = getattr(g, "lsm_maintenance", None)
            maintenance = None
            if lm is not None:
                maintenance = {"steps": lm.steps,
                               "tranquility": round(lm.tranquility, 4),
                               "backlog": engine.get(
                                   "compaction_backlog", 0)}
            from ..utils.metrics import registry as _reg

            phases = {}
            for labels, count, total, mx in _reg().series(
                    "resize_phase_seconds"):
                phases[labels.get("phase", "?")] = {
                    "count": count, "total_s": round(total, 3),
                    "max_s": round(mx, 3)}
            return _json({"engine": engine, "tables": tables,
                          "compaction": maintenance,
                          "resize_phase_seconds": phases})
        if path == "/v1/resize" and m == "GET":
            # operator progress readout for a live layout transition
            # (ISSUE 15 satellite; PR 6 follow-on): phases with timings
            # (from the resize_phase_seconds series the orchestrator
            # records), per-node ack/sync trackers with the LAGGING
            # nodes named per phase, and the rebalance backlog — one
            # call answers "how far along is the resize and who is
            # holding it up".
            g = self.garage
            hist = g.system.layout_manager.history
            helper = g.system.layout_manager.helper
            current = hist.current().version
            min_stored = hist.min_stored()
            trackers = hist.update_trackers
            nodes = []
            for n in sorted(hist.all_storage_nodes()):
                ack = trackers.ack.get(n, min_stored)
                sync = trackers.sync.get(n, min_stored)
                sync_ack = trackers.sync_ack.get(n, min_stored)
                lagging = [ph for ph, v in (("ack", ack),
                                            ("sync", sync),
                                            ("commit", sync_ack))
                           if v < current]
                nodes.append({"node": n.hex()[:16], "ack": ack,
                              "sync": sync, "sync_ack": sync_ack,
                              "lagging": lagging})
            from ..utils.metrics import registry as _reg

            phases = {}
            for labels, count, total, mx in _reg().series(
                    "resize_phase_seconds"):
                phases[labels.get("phase", "?")] = {
                    "count": count, "total_s": round(total, 3),
                    "max_s": round(mx, 3)}
            completed = sum(
                c for _l, c, _t, _m in _reg().series(
                    "resize_transitions_completed"))
            res = g.block_manager.resync
            return _json({
                "layout_version": current,
                "min_stored": min_stored,
                "ack_min": helper.ack_map_min(),
                "sync_min": helper.sync_map_min(),
                "resizing": min_stored < current,
                "phases": phases,
                "transitions_completed": completed,
                "nodes": nodes,
                "rebalance_backlog": res.queue_len(),
                "rebalance_errors": res.errors_len(),
            })

        if path == "/v1/cache" and m == "GET":
            # cache observability (ISSUE 18): per-segment bytes/entries,
            # the cluster tier's lease table + prefetch queue depth, and
            # the node-local singleflight collapse counts — one stop for
            # "is the cold-herd machinery actually engaging?"
            bm = getattr(self.garage, "block_manager", None)
            if bm is None:
                return _json({"enabled": False})
            out = {"enabled": True,
                   "plain": bm.cache.stats(),
                   "singleflight": {
                       "leaders": bm.sf_leaders,
                       "collapsed": bm.sf_collapsed,
                       "in_flight": len(bm._sf),
                   }}
            pc = getattr(bm, "packed_cache", None)
            if pc is not None:
                out["packed"] = pc.stats()
            tier = getattr(bm, "cache_tier", None)
            out["tier"] = tier.stats() if tier is not None else None
            return _json(out)

        if path == "/v1/qos" and m == "GET":
            return _json(self._qos_state())
        if path == "/v1/qos" and m == "POST":
            spec = await body_json() or {}
            qos = getattr(self.garage, "qos", None)
            if qos is None:
                raise BadRequest("qos engine not available")
            gov = getattr(self.garage, "qos_governor", None)
            gov_spec = spec.pop("governor", None)
            sup = getattr(self.garage, "gateway_supervisor", None)
            if sup is not None:
                bad = sorted(k for k in spec
                             if k in ("global_burst",
                                      "global_bytes_burst"))
                if bad:
                    # not silently droppable: leases re-derive burst as
                    # 1s of each worker's granted rate on every renew,
                    # so a fanned-out burst would be overwritten within
                    # one lease interval. Reject before applying
                    # anything so the operator learns the limitation.
                    raise BadRequest(
                        f"{', '.join(bad)} cannot be set in gateway "
                        "mode: worker burst is leased as 1s of each "
                        "worker's granted rate (set global_rps / "
                        "global_bytes_per_s instead)")
            if spec:
                qos.update_limits(spec)
            if sup is not None and spec:
                # node-wide budgets feed the lease broker (each worker
                # learns its new share at its next renew — conservation
                # holds through the change); every other limit applies
                # per worker process and fans out directly
                if "global_rps" in spec:
                    sup.broker.set_totals(rps=spec["global_rps"])
                if "global_bytes_per_s" in spec:
                    sup.broker.set_totals(
                        bytes_per_s=spec["global_bytes_per_s"])
                worker_spec = {k: v for k, v in spec.items()
                               if not k.startswith("global_")}
                if worker_spec:
                    await sup.fanout({"op": "qos", "spec": worker_spec})
            if gov_spec is not None:
                if gov is None:
                    raise BadRequest("governor not running "
                                     "(disabled in config)")
                if isinstance(gov_spec, bool):
                    gov_spec = {"enabled": gov_spec}
                if "enabled" in gov_spec:
                    gov.enabled = bool(gov_spec["enabled"])
                if "target_latency_s" in gov_spec:
                    t = float(gov_spec["target_latency_s"])
                    if t <= 0:
                        raise BadRequest("target_latency_s must be > 0")
                    gov.target_latency = t
                if "scrub_range" in gov_spec:
                    lo, hi = map(float, gov_spec["scrub_range"])
                    gov.scrub_range = (lo, hi)
                if "resync_range" in gov_spec:
                    lo, hi = map(float, gov_spec["resync_range"])
                    gov.resync_range = (lo, hi)
            return _json(self._qos_state())

        if path == "/v1/gateway" and m == "GET":
            # multi-process gateway observability (gateway/supervisor):
            # worker pids/liveness/restarts, per-worker leases, broker
            # conservation. ?detail=1 additionally pulls each live
            # worker's qos + tuning snapshots over RPC.
            sup = getattr(self.garage, "gateway_supervisor", None)
            if sup is None:
                return _json({"enabled": False, "workers": []})
            state = sup.state()
            if q.get("detail"):
                state["worker_qos"] = await sup.fanout(
                    {"op": "qos_state"})
                state["worker_tuning"] = await sup.fanout(
                    {"op": "tuning_state"})
            return _json(state)

        if path in ("/status", "/v1/status") and m == "GET":
            r = await self.rpc.op_status({})
            return _json({
                "node": r["node_id"].hex(),
                "garageVersion": f"garage-tpu-{__version__}",
                "clusterHealth": r["health"],
                "layoutVersion": r["layout_version"],
                "nodes": [{
                    "id": n["id"].hex(),
                    "addr": (f"{n['addr'][0]}:{n['addr'][1]}"
                             if n.get("addr") else None),
                    "isUp": n["is_up"],
                    "hostname": n.get("hostname", ""),
                    "role": n.get("role"),
                } for n in r["nodes"]],
            })
        if path == "/v1/health" and m == "GET":
            h = self.garage.system.health()
            return _json({
                "status": h.status.value,
                "knownNodes": h.known_nodes,
                "connectedNodes": h.connected_nodes,
                "storageNodes": h.storage_nodes,
                "storageNodesOk": h.storage_nodes_up,
                "partitions": 256,
                "partitionsQuorum": h.partitions_quorum,
            })
        if path == "/v1/connect" and m == "POST":
            peers = await body_json() or []
            from ..model.garage import parse_peer

            results = []
            for p in peers:
                try:
                    addr, nid = parse_peer(p)
                    await self.rpc.op_connect(
                        {"addr": list(addr), "id": nid})
                    results.append({"success": True, "error": None})
                except Exception as e:
                    results.append({"success": False, "error": str(e)})
            return _json(results)

        if path == "/v1/layout" and m == "GET":
            r = await self.rpc.op_layout_show({})
            return _json({"version": r["version"], "roles": r["roles"],
                          "stagedRoleChanges": r["staged"]})
        if path == "/v1/layout" and m == "POST":
            changes = await body_json() or []
            for c in changes:
                nid = bytes.fromhex(c["id"])
                if c.get("remove"):
                    await self.rpc.op_layout_remove({"node": nid})
                else:
                    # a role change must be complete — defaulting zone or
                    # capacity would silently relocate/drain the node
                    if "zone" not in c or "capacity" not in c:
                        raise BadRequest(
                            "role change requires zone and capacity "
                            "(capacity null = gateway)")
                    cap = c["capacity"]
                    if isinstance(cap, str):
                        from ..utils.config import parse_capacity

                        cap = parse_capacity(cap)
                    await self.rpc.op_layout_assign({
                        "node": nid, "zone": c["zone"],
                        "capacity": cap,
                        "tags": c.get("tags", []),
                    })
            return _json({"ok": True})
        if path == "/v1/layout/apply" and m == "POST":
            spec = await body_json() or {}
            r = await self.rpc.op_layout_apply(
                {"version": spec.get("version")})
            return _json({"layout": {"version": r["version"]}})
        if path == "/v1/layout/revert" and m == "POST":
            self.garage.system.layout_manager.revert_staged()
            return _json({"ok": True})

        if path == "/v1/key" and m == "GET":
            if q.get("id") or q.get("search"):
                key_id = q.get("id")
                if not key_id:
                    for k in (await self.rpc.op_key_list({}))["keys"]:
                        if k["id"].startswith(q["search"]) \
                                or q["search"] in k["name"]:
                            key_id = k["id"]
                            break
                    if not key_id:
                        raise NoSuchKey(q["search"])
                r = await self.rpc.op_key_info(
                    {"key": key_id,
                     "show_secret": q.get("showSecretKey") == "true"})
                return _json(self._key_info_json(r))
            r = await self.rpc.op_key_list({})
            return _json([{"id": k["id"], "name": k["name"]}
                          for k in r["keys"]])
        if path == "/v1/key" and m == "POST":
            if q.get("id"):
                spec = await body_json() or {}
                if spec.get("allow", {}).get("createBucket"):
                    await self.rpc.op_key_allow({"key": q["id"],
                                                 "create_bucket": True})
                if spec.get("deny", {}).get("createBucket"):
                    await self.rpc.op_key_deny({"key": q["id"],
                                                "create_bucket": True})
                r = await self.rpc.op_key_info({"key": q["id"]})
                return _json(self._key_info_json(r))
            spec = await body_json() or {}
            r = await self.rpc.op_key_new({"name": spec.get("name", "")})
            return _json({"accessKeyId": r["key_id"],
                          "secretAccessKey": r["secret_key"]})
        if path == "/v1/key/import" and m == "POST":
            spec = await body_json() or {}
            r = await self.rpc.op_key_import({
                "key_id": spec["accessKeyId"],
                "secret_key": spec["secretAccessKey"],
                "name": spec.get("name", ""),
            })
            return _json({"accessKeyId": r["key_id"]})
        if path == "/v1/key" and m == "DELETE":
            await self.rpc.op_key_delete({"key": q["id"]})
            return Response(204)

        if path == "/v1/bucket" and m == "GET":
            if q.get("id") or q.get("globalAlias"):
                name = q.get("globalAlias") or q["id"]
                r = await self.rpc.op_bucket_info({"name": name})
                return _json(self._bucket_info_json(r))
            r = await self.rpc.op_bucket_list({})
            return _json([{"id": b["id"], "globalAliases": [b["name"]]}
                          for b in r["buckets"]])
        if path == "/v1/bucket" and m == "PUT" and q.get("id"):
            # UpdateBucket: website access flags + quotas — PUT with id,
            # matching the reference admin v1 route so admin SDKs work
            # (ref: src/api/admin/bucket.rs:405-452 handle_update_bucket)
            bid = bytes.fromhex(q["id"])
            await self.rpc.helper.get_existing_bucket(bid)
            spec = await body_json() or {}
            # validate EVERYTHING first, then apply atomically — a 400
            # must never leave half the update persisted
            updates: dict = {}
            if "websiteAccess" in spec:
                wa = spec["websiteAccess"]
                if not isinstance(wa, dict):
                    raise BadRequest("websiteAccess must be an object")
                if wa.get("enabled"):
                    idx = wa.get("indexDocument")
                    if not idx:
                        raise BadRequest(
                            "indexDocument is required to enable website "
                            "access")
                    updates["website_config"] = {
                        "index_document": idx,
                        "error_document": wa.get("errorDocument")}
                else:
                    updates["website_config"] = None
            if "quotas" in spec:
                qt = spec["quotas"]
                if not isinstance(qt, dict):
                    raise BadRequest("quotas must be an object")
                ms, mo = qt.get("maxSize"), qt.get("maxObjects")
                if (ms is not None and int(ms) <= 0) \
                        or (mo is not None and int(mo) <= 0):
                    raise BadRequest("quota values must be positive")
                updates["quotas"] = {
                    "max_size": int(ms) if ms is not None else None,
                    "max_objects": int(mo) if mo is not None else None}
            if updates:
                await self.rpc.helper.update_bucket_configs(bid, updates)
            r = await self.rpc.op_bucket_info({"name": q["id"]})
            return _json(self._bucket_info_json(r))
        if path == "/v1/bucket" and m == "POST":
            spec = await body_json() or {}
            alias = spec.get("globalAlias")
            if not alias:
                raise BadRequest("globalAlias is required")
            r = await self.rpc.op_bucket_create({"name": alias})
            return _json({"id": r["id"], "globalAliases": [alias]})
        if path == "/v1/bucket" and m == "DELETE":
            await self.rpc.helper.delete_bucket(bytes.fromhex(q["id"]))
            return Response(204)

        if path == "/v1/bucket/allow" and m == "POST":
            spec = await body_json() or {}
            perms = spec.get("permissions", {})
            await self.rpc.op_bucket_allow({
                "bucket": spec["bucketId"], "key": spec["accessKeyId"],
                "read": perms.get("read"), "write": perms.get("write"),
                "owner": perms.get("owner"),
            })
            return _json({"ok": True})
        if path == "/v1/bucket/deny" and m == "POST":
            spec = await body_json() or {}
            perms = spec.get("permissions", {})
            await self.rpc.op_bucket_deny({
                "bucket": spec["bucketId"], "key": spec["accessKeyId"],
                "read": perms.get("read"), "write": perms.get("write"),
                "owner": perms.get("owner"),
            })
            return _json({"ok": True})

        if path == "/v1/bucket/alias/global":
            helper = self.rpc.helper
            bid = bytes.fromhex(q["id"])
            if m == "PUT":
                await helper.global_alias_bucket(bid, q["alias"])
                return _json({"ok": True})
            if m == "DELETE":
                await helper.global_unalias_bucket(bid, q["alias"])
                return _json({"ok": True})
        if path == "/v1/bucket/alias/local":
            helper = self.rpc.helper
            bid = bytes.fromhex(q["id"])
            if m == "PUT":
                await helper.local_alias_bucket(bid, q["accessKeyId"],
                                                q["alias"])
                return _json({"ok": True})
            if m == "DELETE":
                await helper.local_unalias_bucket(bid, q["accessKeyId"],
                                                  q["alias"])
                return _json({"ok": True})

        return None

    def _qos_state(self) -> dict:
        qos = getattr(self.garage, "qos", None)
        gov = getattr(self.garage, "qos_governor", None)
        out = qos.state() if qos is not None else {}
        out["governor"] = gov.state() if gov is not None else None
        sup = getattr(self.garage, "gateway_supervisor", None)
        if sup is not None:
            out["gateway_leases"] = sup.broker.state()
        return out

    async def _check_domain(self, req: Request) -> Response:
        """Website vhost check for reverse proxies; deliberately
        UNAUTHENTICATED like the reference (api_server.rs routes
        CheckDomain before auth — on-demand-TLS issuers don't hold
        admin tokens)."""
        domain = req.query.get("domain", "")
        helper = self.rpc.helper
        name = domain.split(":")[0]
        root = self.garage.config.web_root_domain
        if name.endswith(root):
            name = name[: -len(root)]
        try:
            bid = await helper.resolve_global_bucket_name(name)
            if bid is not None:
                b = await helper.get_existing_bucket(bid)
                if b.params.website_config.value is not None:
                    return Response(200, [], b"Domain is managed\n")
        except (NoSuchBucket, BadRequest):
            pass
        return Response(400, [], b"Domain not managed\n")

    @staticmethod
    def _key_info_json(r: dict) -> dict:
        return {
            "accessKeyId": r["id"], "name": r["name"],
            "secretAccessKey": r.get("secret_key"),
            "permissions": {"createBucket": r.get("create_bucket", False)},
            "buckets": [
                {"id": bid, "permissions": perms}
                for bid, perms in r.get("buckets", {}).items()
            ],
        }

    def render_metrics(self) -> str:
        """Prometheus text exposition from live counters
        (ref: rpc/system_metrics.rs, block/metrics.rs,
        table/metrics.rs)."""
        g = self.garage
        out = []

        def gauge(name, value, help_="", **labels):
            if help_:
                out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} gauge")
            lab = ",".join(f'{k}="{v}"' for k, v in labels.items())
            out.append(f"{name}{{{lab}}} {value}" if lab
                       else f"{name} {value}")

        h = g.system.health()
        gauge("cluster_healthy", 1 if h.status.value == "healthy" else 0,
              "Whether the cluster is fully healthy")
        gauge("cluster_available", 1 if h.status.value != "unavailable" else 0)
        gauge("cluster_known_nodes", h.known_nodes)
        gauge("cluster_connected_nodes", h.connected_nodes)
        gauge("cluster_storage_nodes", h.storage_nodes)
        gauge("cluster_storage_nodes_up", h.storage_nodes_up)
        gauge("cluster_partitions_quorum", h.partitions_quorum)
        gauge("cluster_layout_version",
              g.system.layout_manager.history.current().version)

        out.append("# TYPE block_manager_bytes counter")
        for k, v in g.block_manager.metrics.items():
            gauge(f"block_{k}", v)
        gauge("block_resync_queue_length",
              g.block_manager.resync.queue_len(),
              "Number of blocks in the resync queue")
        gauge("block_resync_errored_blocks",
              g.block_manager.resync.errors_len())
        # the resize plane watches the same queue under its own name:
        # during a layout transition this IS the rebalance backlog,
        # and "backlog drained to zero" is the smoke/soak assertion
        gauge("resync_backlog", g.block_manager.resync.queue_len(),
              "Rebalance/resync backlog (blocks awaiting "
              "re-examination)")
        gauge("resize_layout_min_stored",
              g.system.layout_manager.history.min_stored(),
              "Oldest live layout version (== current once a resize "
              "fully commits)")
        gauge("resize_layout_ack_min",
              g.system.layout_manager.helper.ack_map_min())
        gauge("resize_layout_sync_min",
              g.system.layout_manager.helper.sync_map_min())
        # hot-block read cache (block/cache.py): cache_hits/misses/
        # evictions/bytes + admission counters
        out.append("# TYPE cache_hits counter")
        for k, v in g.block_manager.cache.stats().items():
            gauge(f"cache_{k}", v)
        # cluster cache tier (block/cache_tier.py): probe economics +
        # hint-gossip visibility; cache_tier_enabled is the smoke
        # assertion that the tier plane exists
        tier = getattr(g.block_manager, "cache_tier", None)
        gauge("cache_tier_enabled",
              1 if tier is not None and tier.enabled else 0,
              "Whether the cluster-wide cache tier is active")
        if tier is not None:
            ts = tier.stats()
            gauge("cache_tier_members", ts["members"])
            gauge("cache_tier_probes", ts["probes"])
            gauge("cache_tier_probe_hits", ts["probe_hits"])
            gauge("cache_tier_probe_misses", ts["probe_misses"])
            gauge("cache_tier_probe_fails", ts["probe_fails"])
            gauge("cache_tier_remote_hit_bytes", ts["remote_hit_bytes"])
            gauge("cache_tier_inserts_pushed", ts["inserts_pushed"])
            gauge("cache_tier_hints_known", ts["hints_known"])
            gauge("cache_tier_hints_seen", ts["hints_seen"])
            # probe singleflight leases + hint prefetch (ISSUE 18):
            # the cold-herd plane — lease table depth and queue length
            # are the live-pressure gauges, the counters are the
            # collapse economics the flash-crowd drill asserts on
            gauge("cache_lease_wait_ms_configured", ts["lease_wait_ms"])
            gauge("cache_lease_table_depth", ts["lease_depth"],
                  "Live probe leases at this owner")
            gauge("cache_lease_minted_total", ts["lease_minted"])
            gauge("cache_lease_resolved_total", ts["lease_resolved"])
            gauge("cache_lease_expired_total", ts["lease_expired"])
            gauge("cache_lease_waits_total", ts["lease_waits"])
            gauge("cache_lease_grants_total", ts["lease_grants"])
            gauge("cache_lease_wait_hits_total", ts["lease_wait_hits"])
            gauge("cache_lease_wait_timeouts_total",
                  ts["lease_wait_timeouts"])
            gauge("cache_prefetch_queue_depth", ts["prefetch_queue"],
                  "Hinted hashes awaiting background prefetch")
            gauge("cache_prefetch_done_total", ts["prefetched"])
            gauge("cache_prefetch_skips_total", ts["prefetch_skips"])
            gauge("cache_prefetch_drops_total", ts["prefetch_drops"])
            gauge("cache_prefetch_errors_total", ts["prefetch_errors"])
        # packed-bytes tier segment + node-local read singleflight
        # (ISSUE 18)
        pc = getattr(g.block_manager, "packed_cache", None)
        if pc is not None:
            gauge("cache_packed_bytes", pc.bytes_used,
                  "Packed-bytes tier segment resident bytes")
            gauge("cache_packed_entries", pc.entries)
            gauge("cache_packed_max_bytes", pc.max_bytes)
            gauge("cache_packed_inserts_total", pc.inserts)
            gauge("cache_packed_hits_total", pc.hits)
        gauge("cache_sf_leaders_total",
              getattr(g.block_manager, "sf_leaders", 0),
              "Node-local read singleflight: store reads led")
        gauge("cache_sf_collapsed_total",
              getattr(g.block_manager, "sf_collapsed", 0),
              "Node-local read singleflight: reads collapsed onto "
              "a leader")
        sw = g.block_manager.scrub_worker
        if sw is not None:
            out.append("# HELP block_scrub_corruptions "
                       "Corruptions found across all scrub passes")
            out.append("# TYPE block_scrub_corruptions counter")
            gauge("block_scrub_corruptions", sw.state.corruptions)
            out.append("# TYPE block_scrub_deep_stripes_checked counter")
            gauge("block_scrub_deep_stripes_checked", sw.deep_checked)
            out.append("# TYPE block_scrub_deep_stripes_repaired counter")
            gauge("block_scrub_deep_stripes_repaired", sw.deep_repaired)
            out.append("# TYPE block_scrub_header_repaired counter")
            gauge("block_scrub_header_repaired", sw.header_repaired)
            out.append("# TYPE block_scrub_cache_lookups counter")
            gauge("block_scrub_cache_lookups", sw.scrub_cache_lookups)
            out.append("# TYPE block_scrub_cache_hits counter")
            gauge("block_scrub_cache_hits", sw.scrub_cache_hits)

        for t in g.all_tables():
            s = t.data.stats()
            for k, v in s.items():
                gauge(f"table_{k}", v, table=t.name)
            gauge("table_size_bytes", t.data.size_bytes(), table=t.name)

        # metadata engine internals (db/lsm.py et al.; README "Metadata
        # at scale") — segment count, compaction backlog and WAL size
        # make compaction stalls and flush storms visible to operators
        es = g.db.engine_stats()
        gauge("meta_rows", es.get("rows", 0),
              "Live rows across all metadata trees",
              engine=es.get("engine", "?"))
        for k in ("segments", "compaction_backlog", "wal_bytes",
                  "memtable_bytes", "flushes", "compactions",
                  "file_bytes"):
            if k in es:
                gauge(f"meta_{k}", es[k], engine=es["engine"])
        lm = getattr(g, "lsm_maintenance", None)
        if lm is not None:
            gauge("meta_compaction_tranquility",
                  round(lm.tranquility, 4))

        # per-node status + ping gauges (ref: rpc/system_metrics.rs:302)
        for peer in g.system.peering.get_peer_list():
            nid = peer.id.hex()[:16]
            gauge("cluster_node_up",
                  1 if peer.state.value == "connected"
                  or peer.id == g.system.id else 0, node=nid)
            if peer.ping_avg is not None:
                gauge("cluster_node_ping_avg_seconds", round(peer.ping_avg, 6),
                      node=nid)
            if peer.ping_max is not None:
                gauge("cluster_node_ping_max_seconds", round(peer.ping_max, 6),
                      node=nid)

        # qos admission-control plane (garage_tpu/qos/)
        qos = getattr(g, "qos", None)
        if qos is not None:
            c = qos.counters
            out.append("# TYPE qos_requests counter")
            gauge("qos_admitted_total", c.admitted)
            gauge("qos_shed_total", c.shed)
            gauge("qos_queued_waits_total", c.queued_waits)
            gauge("qos_queued_seconds_total",
                  round(c.queued_seconds, 6))
            gauge("qos_shaped_bytes_total", c.shaped_bytes)
            for scope, n in c.shed_by_scope.items():
                gauge("qos_shed_by_scope", n, scope=scope)
            if qos._conc is not None:
                gauge("qos_in_flight", qos._conc.active)
                gauge("qos_queued", qos._conc.queued)
        gov = getattr(g, "qos_governor", None)
        if gov is not None:
            gauge("qos_governor_pressure_current",
                  round(gov.pressure, 4))
            gauge("qos_governor_queue_depth", gov.last_queue_depth)
            if gov.ewma is not None:
                gauge("qos_governor_ewma_latency_seconds",
                      round(gov.ewma, 6))

        # chaos fault injection (garage_tpu/chaos/) — always exported,
        # so dashboards/smoke can assert the plane exists even at zero
        from ..chaos import injector as chaos_inj

        ctl = chaos_inj.controller()
        gauge("chaos_enabled", 1 if chaos_inj.ACTIVE is not None else 0,
              "Whether fault injection is armed")
        gauge("chaos_faults_armed", len(ctl.faults))
        gauge("chaos_fired_total", ctl.total_fired,
              "Total injected faults that actually fired")

        # self-healing rpc: hedge + breaker counters and per-peer
        # breaker state (0 closed, 1 half-open, 2 open)
        health = g.system.peering.health
        hs = health.stats()
        gauge("rpc_hedging_enabled", 1 if hs["hedging_enabled"] else 0)
        gauge("rpc_hedge_launched_total", hs["hedges_launched"],
              "Backup requests launched by hedged reads")
        gauge("rpc_hedge_wins_total", hs["hedge_wins"])
        gauge("rpc_breaker_open_total", hs["breaker_opens"],
              "Circuit breaker open transitions")
        gauge("rpc_breaker_close_total", hs["breaker_closes"])
        _brk_num = {"closed": 0, "half_open": 1, "open": 2}
        for nid, st in health.peer_state().items():
            gauge("rpc_breaker_state", _brk_num[st["breaker"]], node=nid)
            if st["p99_s"] is not None:
                gauge("rpc_peer_p99_seconds", round(st["p99_s"], 6),
                      node=nid)

        # multi-process gateway supervisor (gateway/supervisor.py):
        # worker liveness + lease ledger. conservation_ok == 1 is the
        # smoke/soak assertion that Σ(worker leases) never exceeded the
        # node budget, including across worker kills.
        sup = getattr(g, "gateway_supervisor", None)
        if sup is not None:
            st = sup.state()
            gauge("gateway_workers_configured", st["workers_configured"],
                  "Gateway worker processes configured")
            gauge("gateway_workers_alive", st["workers_alive"])
            gauge("gateway_worker_restarts_total", st["restarts_total"],
                  "Worker processes respawned after a crash")
            gauge("gateway_lease_conservation_ok",
                  1 if st["broker"]["conservation_ok"] else 0,
                  "Whether sum(worker leases) <= node budget held")
            for dim, metric in (("rps", "gateway_lease_rps"),
                                ("bytes_per_s",
                                 "gateway_lease_bytes_per_s")):
                d = st["broker"][dim]
                for w, v in d["granted"].items():
                    gauge(metric, v, worker=w.lstrip("w"))
                if d["pool_free"] is not None:
                    gauge(metric + "_free", d["pool_free"])
            for w in st["workers"]:
                gauge("gateway_worker_up", 1 if w["alive"] else 0,
                      worker=str(w["index"]))

        # op counters/durations from the process-wide registry
        # (rpc/table/api/block series; ref: rpc/metrics.rs etc.)
        from ..utils.metrics import registry

        out.extend(registry().render())

        # device feeder calibration + staged-pipeline observability.
        # Names are registered literally (GL07-checkable, and `feeder`
        # is in METRIC_NAME_RE) — the old `gauge(f"feeder_{k}")` loop
        # was a dynamic name no static rule could audit.
        feeder = g.block_manager.feeder
        for opbe, mbps in feeder.perf_summary().items():
            op, _, be = opbe.partition("/")
            gauge("feeder_throughput_mbps", mbps, op=op, backend=be)
        fs = feeder.stats
        gauge("feeder_batches", fs["batches"],
              "Batches dispatched by the device feeder")
        gauge("feeder_items", fs["items"])
        gauge("feeder_device_batches", fs["device_batches"])
        gauge("feeder_device_items", fs["device_items"],
              "Items that actually ran on the device path (the live "
              "TPU-engagement proof metric)")
        gauge("feeder_device_bytes", fs["device_bytes"])
        gauge("feeder_inline_items", fs["inline_items"])
        gauge("feeder_max_batch", fs["max_batch"])
        gauge("feeder_pad_waste_bytes", fs["pad_waste_bytes"],
              "Zero-padding bytes added by fixed-shape bucket launches")
        gauge("feeder_recompiles", fs["recompiles"],
              "Distinct launch shapes seen (each one XLA compile)")
        gauge("feeder_mesh_batches", fs["mesh_batches"],
              "Device batches sharded across the multi-chip mesh")
        gauge("feeder_decode_items", fs["decode_items"],
              "Decode/repair items through the feeder (degraded GETs "
              "+ scrub/resync rebuilds)")
        gauge("feeder_decode_device_items", fs["decode_device_items"],
              "Decode/repair items that ran on the device path (the "
              "read-side engagement proof metric)")
        gauge("feeder_decode_device_bytes", fs["decode_device_bytes"])
        ps = feeder.pipeline_stats()
        gauge("feeder_inflight", ps["inflight"],
              "Batches currently in flight through the staged pipeline")
        gauge("feeder_pipeline_wall_seconds", ps["wall_s"],
              "Wall-clock union of windows with a device leg in flight")
        gauge("feeder_overlap_efficiency", ps["overlap_efficiency"],
              "Sum of stage-busy seconds / wall (>1 = stages overlap)")
        for stage, s in ps["busy_s"].items():
            gauge("feeder_pipeline_busy_seconds", s, stage=stage)

        for wid, info in g.runner.worker_info().items():
            gauge("worker_busy", 1 if info.state == "busy" else 0,
                  worker=info.name)
            if info.queue_length is not None:
                gauge("worker_queue_length", info.queue_length,
                      worker=info.name)
            gauge("worker_errors", info.errors, worker=info.name)
        return "\n".join(out) + "\n"
