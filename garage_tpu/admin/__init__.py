"""Admin control surface: in-cluster RPC + HTTP admin/metrics API."""

from .rpc import AdminRpcHandler

__all__ = ["AdminRpcHandler"]
