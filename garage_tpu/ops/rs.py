"""Cauchy-Reed-Solomon (k, m) erasure codec, batched on TPU.

Construction: systematic generator G (n x k, n = k + m) = [I_k ; C] with
C the m x k Cauchy matrix C[i, j] = 1 / (x_i + y_j), x_i = i,
y_j = m + j over GF(2^8). Every square submatrix of a Cauchy matrix is
nonsingular, so any k of the n shards reconstruct the stripe (MDS).

Shapes: a *stripe* is (k, shard_len) bytes of data producing (m,
shard_len) parity; all ops take arbitrary leading batch dims so a whole
batch of 1-16 MiB blocks is one MXU matmul (see gf256.bit_matmul_apply).
Decode/repair matrices depend on *which* shards survive; they are built
host-side per erasure pattern (k x k inversion, microseconds) and
cached — but on device they travel as DATA (gf_apply_batched /
gf256.bit_matmul_apply_batched), so one compiled XLA program serves
every pattern; only the encode/parity constants are baked into traces.

This is the math behind the `erasure(k, m)` replication mode — the north
star's addition at the reference's plugin boundary
(src/rpc/replication_mode.rs:8-20, which only offers replicate-N).
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf256


@functools.lru_cache(maxsize=None)
def generator_matrix(k: int, m: int) -> np.ndarray:
    """(k+m, k) systematic generator over GF(2^8): identity over Cauchy."""
    if k < 1 or m < 0 or k + m > 256:
        raise ValueError(f"need 1 <= k, 0 <= m, k+m <= 256; got k={k} m={m}")
    x = np.arange(m, dtype=np.uint8)[:, None]  # parity row ids
    y = np.arange(m, m + k, dtype=np.uint8)[None, :]  # data col ids
    cauchy = gf256.gf_inv(x ^ y)
    return np.concatenate([np.eye(k, dtype=np.uint8), cauchy], axis=0)


@functools.lru_cache(maxsize=None)
def parity_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) Cauchy part of the generator."""
    return np.ascontiguousarray(generator_matrix(k, m)[k:])


@functools.lru_cache(maxsize=None)
def decode_matrix(k: int, m: int, present: tuple[int, ...]) -> np.ndarray:
    """(k, k) matrix mapping k surviving shards (rows `present` of G,
    ascending) back to the k data shards."""
    if len(present) != k:
        raise ValueError(f"need exactly k={k} shard indices, got {len(present)}")
    sub = generator_matrix(k, m)[list(present)]
    return gf256.gf_inv_matrix(sub)


@functools.lru_cache(maxsize=None)
def repair_matrix(
    k: int, m: int, present: tuple[int, ...], missing: tuple[int, ...]
) -> np.ndarray:
    """(len(missing), k) matrix rebuilding the `missing` shards directly
    from the k `present` ones (data and parity alike)."""
    g = generator_matrix(k, m)
    return gf256.gf_matmul(g[list(missing)], decode_matrix(k, m, present))


@functools.lru_cache(maxsize=None)
def decode_bitmat_t(k: int, m: int, present: tuple[int, ...]) -> np.ndarray:
    """(8k, 8k) int8 transposed bit-expansion of decode_matrix — the
    per-item DATA operand of the pattern-as-data batched kernel
    (gf_apply_batched). Host-side and lru-cached like the matrix
    itself: the inversion plus expansion is microseconds, and caching
    keys on the pattern tuple so a busy mixed-pattern read path builds
    each expansion once."""
    return gf256.bitmat_t_for(decode_matrix(k, m, present))


@functools.lru_cache(maxsize=None)
def repair_bitmat_t(k: int, m: int, present: tuple[int, ...],
                    missing: tuple[int, ...]) -> np.ndarray:
    """(8k, 8·len(missing)) int8 transposed bit-expansion of
    repair_matrix, for the batched repair launch."""
    return gf256.bitmat_t_for(repair_matrix(k, m, present, missing))


# ---------------------------------------------------------------------------
# Device (JAX) paths — jitted per (k, m[, pattern]); batched over stripes
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _jit_apply(key, matrix_bytes, rows: int, cols: int):
    """One jitted bit-matmul per distinct GF matrix. `key` keeps cache
    entries readable; the matrix travels as bytes to stay hashable."""
    import jax

    mat = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(rows, cols)
    bitmat_t = gf256.bitmat_t_for(mat)

    @jax.jit
    def apply(x):
        return gf256.bit_matmul_apply(bitmat_t, x)

    return apply


_pallas_ok: bool | None = None


def _try_pallas(mat: np.ndarray, x):
    """Fused Pallas kernel (ops/pallas_gf.py), opt-in via
    GARAGE_TPU_PALLAS=1 on a real TPU. Measured on v5e-1 with
    dependency-chained iterations (dispatch overhead amortized, no
    async-overlap artifacts): XLA bit-matmul 15.5 GB/s vs Pallas
    13.0 GB/s for RS(10,4) encode — XLA's fusion wins once the encode
    is embedded in a larger jitted program, so it stays the default;
    the kernel remains available for standalone-call workloads where
    its single-pass HBM profile helps."""
    global _pallas_ok
    if _pallas_ok is False:
        return None
    import os

    if not os.environ.get("GARAGE_TPU_PALLAS"):
        return None
    shape = getattr(x, "shape", ())
    if len(shape) < 2:
        return None
    n = shape[-1]
    from . import pallas_gf

    if n % 256 or n < 256:
        return None
    if _pallas_ok is None:
        if not pallas_gf.available():
            _pallas_ok = False
            return None
    x3 = x.reshape((-1,) + tuple(shape[-2:]))
    try:
        out = pallas_gf.gf_apply(mat, x3)
        _pallas_ok = True
    except Exception:
        # first failure disables the kernel for the process (a broken
        # Mosaic path must not retry-compile per call)
        _pallas_ok = False
        return None
    return out.reshape(tuple(shape[:-2]) + (mat.shape[0], n))


def _apply(tag: str, mat: np.ndarray, x):
    out = _try_pallas(mat, x)
    if out is not None:
        return out
    fn = _jit_apply((tag, mat.shape), mat.tobytes(), *mat.shape)
    return fn(x)


@functools.lru_cache(maxsize=None)
def _jit_gf_apply_batched():
    """THE pattern-as-data kernel: one jitted batched GF apply for all
    erasure patterns. The per-item bit-matrices are a tensor operand,
    so jit keys on SHAPES only — (batch bucket, k, rows, shard-len
    bucket) — never on which shards survived. One compiled program per
    shape serves every present-set; the feeder's pad-bucket ladder
    keeps the shape set finite."""
    import jax

    @jax.jit
    def apply(bitmats_t, x):
        return gf256.bit_matmul_apply_batched(bitmats_t, x)

    return apply


def gf_apply_batched(bitmats_t, shards):
    """Per-stripe GF maps, batched: bitmats_t (B, 8s, 8r) int8 +
    shards (B, s, n) uint8 -> (B, r, n) uint8 on device."""
    return _jit_gf_apply_batched()(bitmats_t, shards)


def _apply_pattern(bitmat_t: np.ndarray, x):
    """Apply ONE pattern's bit-matrix to a (..., s, n) batch through
    the pattern-as-data kernel (matrix broadcast over the batch). The
    predecessor jitted per pattern (`f"dec{k},{m},{present}"` keys):
    every distinct erasure pattern grew the jit cache and paid a fresh
    XLA compile — unbounded across C(k+m, k) patterns."""
    shape = tuple(x.shape)
    x3 = x.reshape((-1,) + shape[-2:])
    mats = np.ascontiguousarray(
        np.broadcast_to(bitmat_t, (x3.shape[0],) + bitmat_t.shape))
    out = gf_apply_batched(mats, x3)
    return out.reshape(shape[:-2] + tuple(out.shape[-2:]))


def encode(k: int, m: int, data):
    """data (..., k, n) uint8 -> parity (..., m, n) uint8 on device."""
    return _apply(f"enc{k},{m}", parity_matrix(k, m), data)


def decode(k: int, m: int, present: tuple[int, ...], shards):
    """shards (..., k, n) = surviving shard rows in ascending-index order
    -> data (..., k, n). Pattern-as-data: every present-set shares one
    compiled program per shape (the constant-matrix form leaked a jit
    cache entry + compile per pattern)."""
    return _apply_pattern(decode_bitmat_t(k, m, tuple(present)), shards)


def repair(k: int, m: int, present: tuple[int, ...], missing: tuple[int, ...], shards):
    """shards (..., k, n) -> rebuilt missing shards (..., len(missing), n).
    Pattern-as-data like decode."""
    return _apply_pattern(
        repair_bitmat_t(k, m, tuple(present), tuple(missing)), shards)


@functools.lru_cache(maxsize=None)
def _jit_parity_check(k: int, m: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chk(stripes):
        p2 = encode(k, m, stripes[:, :k, :])
        return jnp.all(p2 == stripes[:, k:, :], axis=(1, 2))

    return chk


def parity_check(k: int, m: int, stripes):
    """stripes (B, k+m, n) uint8 -> (B,) bool: stored parity equals
    parity re-derived from the data shards — ONE fused device pass (the
    scrub detect kernel). A corrupt *data* shard flips every re-derived
    parity row (each parity is a function of all k data shards); a
    corrupt *parity* row differs only in itself — either way at least
    one row mismatches, so any single corruption is detected, but
    localization needs the decode sweep in repair.py. Zero-padding
    stripes to a common n is safe: the code is linear, so zero data
    rows encode to zero parity rows."""
    return _jit_parity_check(k, m)(stripes)


# ---------------------------------------------------------------------------
# Host (numpy) reference + small-input fallback
# ---------------------------------------------------------------------------


def encode_np(k: int, m: int, data: np.ndarray) -> np.ndarray:
    """Table-lookup reference: data (k, n) -> parity (m, n)."""
    return gf256.gf_matmul(parity_matrix(k, m), np.asarray(data, dtype=np.uint8))


def decode_np(k: int, m: int, present: tuple[int, ...], shards: np.ndarray) -> np.ndarray:
    return gf256.gf_matmul(decode_matrix(k, m, present), np.asarray(shards, dtype=np.uint8))


def repair_np(k: int, m: int, present: tuple[int, ...],
              missing: tuple[int, ...], shards: np.ndarray) -> np.ndarray:
    """Host reference: rebuild the `missing` rows directly from the k
    `present` ones (one matmul by the precomposed repair matrix)."""
    return gf256.gf_matmul(repair_matrix(k, m, present, missing),
                           np.asarray(shards, dtype=np.uint8))


# ---------------------------------------------------------------------------
# Stripe layout helpers (byte-level, host)
# ---------------------------------------------------------------------------


def shard_len(block_len: int, k: int) -> int:
    return (block_len + k - 1) // k


def split_stripe(data: bytes, k: int) -> np.ndarray:
    """bytes -> (k, shard_len) uint8, zero-padded. Original length is
    metadata the block layer stores alongside (block/codec.py)."""
    n = shard_len(len(data), k)
    buf = np.zeros(k * n, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    return buf.reshape(k, n)


def join_stripe(shards: np.ndarray, block_len: int) -> bytes:
    return np.asarray(shards, dtype=np.uint8).reshape(-1)[:block_len].tobytes()
