"""Batched SHA-256 for the device feeder (ISSUE 17).

SigV4 streaming uploads (aws-chunked) sign every client chunk with a
SHA-256 of its payload — on the PUT hot path that is a second full walk
over every body byte, serial per stream. One stream's chunk hashes form
a chain only through the *signature*, not the digest: each chunk's
SHA-256 is independent, so chunk hashes from CONCURRENT streams batch
into one launch exactly like the content-hash lanes.

Formulation follows ops/treehash.py's lane-major rule: the batch is the
trailing axis of every array, and the per-round body is a lax.scan so
the HLO stays ~60 ops regardless of batch size (a fully unrolled 64
round x 48 schedule u32 chain sends XLA:CPU into multi-minute
compiles). The block axis is ALSO a scan, with per-row active masks so
the block count can pad up to a power of two — one compiled program
per (block bucket, item bucket) instead of one per distinct chunk
size. SHA padding (0x80 + 64-bit big-endian bit length) is written
host-side at the TRUE message end; rows past a message's final block
compress into a state the mask then discards.

The pure-Python hashlib path stays the host route and the test oracle.
"""

from __future__ import annotations

import functools
import hashlib

import numpy as np

# FIPS 180-4 constants
K = np.array([
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
    0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
    0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
    0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
    0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
    0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
    0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
    0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
    0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
    0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
    0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
], dtype=np.uint32)

H0 = np.array([
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
], dtype=np.uint32)

BLOCK = 64  # compression block bytes


def n_blocks_for(length: int) -> int:
    """Blocks the padded message occupies: data + 0x80 + u64 bit
    length, rounded up to 64."""
    return (length + 9 + BLOCK - 1) // BLOCK


def blocks_bucket(n_blocks: int, minimum: int = 16) -> int:
    """Next power of two >= n_blocks (min 1 KiB of message): the block
    axis is masked per row, so rounding it up costs only zero-block
    compressions the mask discards — and keeps the compile count
    logarithmic in chunk size instead of linear."""
    b = minimum
    while b < n_blocks:
        b <<= 1
    return b


def part_len(data) -> int:
    """Length of one message: bytes/buffer, or a list/tuple of spans —
    the zero-copy aws-chunked path hands a client chunk as the spans it
    landed in the lease (contiguous there, but framed per socket read),
    and concatenating them host-side would be exactly the copy the
    ingest path exists to avoid."""
    if isinstance(data, (list, tuple)):
        return sum(len(p) for p in data)
    return len(data)


def pad_row_into(row: np.ndarray, data) -> int:
    """Write `data` + SHA padding into a ZEROED row of >= n_blocks*64
    bytes; -> the row's true block count. `data` may be bytes, any
    contiguous buffer (the zero-copy PUT path hands leased views), or a
    list/tuple of spans hashed as one message — the row IS the h2d
    staging buffer, so writing spans sequentially here is the one place
    scattered wire bytes become a device-shaped message for free."""
    off = 0
    for part in (data if isinstance(data, (list, tuple)) else (data,)):
        arr = np.frombuffer(part, dtype=np.uint8)
        row[off:off + arr.size] = arr
        off += arr.size
    nb = n_blocks_for(off)
    row[off] = 0x80
    end = nb * BLOCK
    row[end - 8:end] = np.frombuffer(
        (off * 8).to_bytes(8, "big"), dtype=np.uint8)
    return nb


def hash_rows(msgs, nblocks, n_pad_blocks: int):
    """Traceable batched SHA-256: msgs (B, n_pad_blocks*64) u8 padded
    rows + (B,) i32 true block counts -> (B, 8) u32 big-endian digest
    words. Rows must carry their own SHA padding (pad_row_into) and be
    zero past it."""
    import jax
    import jax.numpy as jnp

    u32 = jnp.uint32
    b = msgs.shape[0]
    w = msgs.reshape(b, n_pad_blocks, 16, 4).astype(u32)
    # big-endian words, lane-major: (blocks, 16, B)
    words = ((w[..., 0] << 24) | (w[..., 1] << 16)
             | (w[..., 2] << 8) | w[..., 3]).transpose(1, 2, 0)
    kj = jnp.asarray(K)

    def rotr(x, n):
        return (x >> u32(n)) | (x << u32(32 - n))

    def round_body(carry, kt):
        st, w16 = carry  # (8, B) working vars, (16, B) schedule ring
        wt = w16[0]
        # W[t+16] from the ring: W[t] + s0(W[t+1]) + W[t+9] + s1(W[t+14])
        s0 = rotr(w16[1], 7) ^ rotr(w16[1], 18) ^ (w16[1] >> u32(3))
        s1 = rotr(w16[14], 17) ^ rotr(w16[14], 19) ^ (w16[14] >> u32(10))
        w16 = jnp.concatenate([w16[1:], (w16[0] + s0 + w16[9] + s1)[None]])
        a, bb, c, d, e, f, g, h = st
        t1 = (h + (rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25))
              + ((e & f) ^ (~e & g)) + kt + wt)
        t2 = ((rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22))
              + ((a & bb) ^ (a & c) ^ (bb & c)))
        st = jnp.stack([t1 + t2, a, bb, c, d + t1, e, f, g])
        return (st, w16), None

    def block_body(h, xs):
        wb, act = xs  # (16, B) message words, (B,) active mask
        (st, _), _ = jax.lax.scan(round_body, (h, wb), kj)
        return jnp.where(act, h + st, h), None

    active = (jnp.arange(n_pad_blocks, dtype=jnp.int32)[:, None]
              < nblocks[None, :])  # (blocks, B)
    h0 = jnp.tile(jnp.asarray(H0)[:, None], (1, b))
    h, _ = jax.lax.scan(block_body, h0, (words, active))
    return h.T  # (B, 8)


@functools.lru_cache(maxsize=None)
def hash_fn(n_pad_blocks: int):
    """Jitted (B, n_pad_blocks*64) u8 + (B,) i32 -> (B, 8) u32; one
    program per block bucket (hash_rows masks the tail)."""
    import jax

    return jax.jit(functools.partial(hash_rows,
                                     n_pad_blocks=n_pad_blocks))


def digests_to_hex(cvs) -> list[str]:
    """(B, 8) u32 digest words -> per-row lowercase hex."""
    arr = np.ascontiguousarray(np.asarray(cvs).astype(">u4"))
    rows = arr.view(np.uint8).reshape(arr.shape[0], 32)
    return [rows[i].tobytes().hex() for i in range(rows.shape[0])]


def sha256_hex_many(blobs: list) -> list[str]:
    """Device-batched hex digests (stage + launch + readback fused —
    the synchronous test/bench entry; the staged backend calls the
    pieces so d2h overlaps the next batch's h2d)."""
    out: list = [None] * len(blobs)
    groups: dict[int, list[int]] = {}
    for i, d in enumerate(blobs):
        groups.setdefault(
            blocks_bucket(n_blocks_for(part_len(d))), []).append(i)
    for npad, idxs in groups.items():
        buf = np.zeros((len(idxs), npad * BLOCK), dtype=np.uint8)
        nbs = np.empty(len(idxs), dtype=np.int32)
        for row, i in enumerate(idxs):
            nbs[row] = pad_row_into(buf[row], blobs[i])
        for i, hx in zip(idxs, digests_to_hex(hash_fn(npad)(buf, nbs))):
            out[i] = hx
    return out


def sha256_hex_py(data) -> str:
    """Host oracle/fallback (accepts span lists like the device path)."""
    h = hashlib.sha256()
    for part in (data if isinstance(data, (list, tuple)) else (data,)):
        h.update(part)
    return h.hexdigest()
