"""ops — the TPU data plane.

This package is the reason this framework exists: the reference (Garage,
Rust) does all block math — content hashing (src/util/data.rs:124), zstd
compression (src/block/block.rs:85), and has NO erasure coding at all — on
CPU, one block at a time. Here the block data path is batched math on TPU:

  gf256.py    GF(2^8) arithmetic + the GF(2) bit-matrix formulation that
              turns erasure coding into int8 matmuls on the MXU
  rs.py       Cauchy-Reed-Solomon (k, m) codec: encode / decode / repair,
              batched over stripes (the `erasure(k,m)` replication mode
              the north star adds next to the reference's replicate-N,
              plugged in at src/rpc/replication_mode.rs:8)
  treehash.py BLAKE3 tree hashing in JAX: 1 MiB block = 1024 chunks
              compressed in parallel on the VPU (replaces the reference's
              sequential blake2 block hash, src/block/manager.rs:554)
  pallas_gf.py fused Pallas TPU kernel for GF(2^8) matrix application:
              unpack -> MXU matmul -> pack entirely in VMEM, cutting HBM
              traffic ~9x vs the XLA bit-matmul path (measured on v5e-1:
              8.4 vs 5.6 GB/s RS(10,4) encode); rs.py auto-selects it on
              real TPU backends with XLA as the universal fallback
"""
