"""GF(2^8) arithmetic and the MXU-friendly GF(2) bit-matrix formulation.

The field is GF(2^8) with the primitive polynomial 0x11D
(x^8 + x^4 + x^3 + x^2 + 1) — the polynomial used by most storage
erasure-coding libraries. alpha = 2 is a primitive element.

Two layers:

1. Host-side (numpy): exp/log tables, vectorized mul/div, Gauss-Jordan
   matrix inversion. Used to build/invert generator matrices — tiny
   (k+m <= 256 square), so this never needs the TPU.

2. Device-side: the *bit-matrix trick*. Multiplication by a constant c in
   GF(2^8) is linear over GF(2): writing a byte as a bit-vector
   b = (b0..b7), c*b = M_c @ b  (mod 2) where M_c is an 8x8 0/1 matrix
   whose column j holds the bits of c * x^j. A whole GF(2^8) matrix
   A (r x s) therefore expands to a GF(2) matrix bits(A) (8r x 8s), and

       A @ X  over GF(2^8)  ==  pack( bits(A) @ unpack(X)  mod 2 )

   which is an ordinary int8 matmul + parity — exactly what the TPU MXU
   eats. No per-byte table lookups (gathers are slow on TPU), no custom
   field ops: encode/decode of arbitrarily wide stripes becomes one
   (N, 8s) @ (8s, 8r) matmul with int32 accumulation and an AND 1.

No reference analogue: Garage has no erasure coding (SURVEY.md §2.11
item 8); this implements the north star's new math from scratch.
"""

from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, primitive
GF_ORDER = 255  # multiplicative group order


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """exp/log tables for alpha=2. exp is doubled to 510 entries so
    exp[log[a] + log[b]] needs no modular reduction."""
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(GF_ORDER):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    for i in range(GF_ORDER, 512):
        exp[i] = exp[i - GF_ORDER]
    log[0] = -1  # sentinel; callers must special-case zero
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a, b):
    """Element-wise GF(2^8) multiply; numpy arrays or scalars (uint8)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    out = GF_EXP[GF_LOG[a] + GF_LOG[b]]
    return np.where((a == 0) | (b == 0), np.uint8(0), out)


def gf_inv(a):
    a = np.asarray(a, dtype=np.uint8)
    if np.any(a == 0):
        raise ZeroDivisionError("gf_inv(0)")
    return GF_EXP[GF_ORDER - GF_LOG[a]]


def gf_div(a, b):
    return gf_mul(a, gf_inv(b))


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Dense GF(2^8) matrix product (host-side, small matrices only)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    # (r, s, 1) x (1, s, c) -> sum over s with XOR reduction
    prod = gf_mul(a[:, :, None], b[None, :, :])
    return np.bitwise_xor.reduce(prod, axis=1)


def gf_inv_matrix(a: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^8). Raises if singular."""
    a = np.asarray(a, dtype=np.uint8)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"square matrix required, got {a.shape}")
    aug = np.concatenate([a.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv_rows = np.nonzero(aug[col:, col])[0]
        if piv_rows.size == 0:
            raise np.linalg.LinAlgError("singular matrix over GF(2^8)")
        piv = col + int(piv_rows[0])
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        aug[col] = gf_mul(aug[col], gf_inv(aug[col, col]))
        for row in range(n):
            if row != col and aug[row, col] != 0:
                aug[row] = aug[row] ^ gf_mul(aug[row, col], aug[col])
    return aug[:, n:]


# ---------------------------------------------------------------------------
# GF(2) bit-matrix expansion
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _mul_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix M_c with (M_c @ bits(b)) % 2 == bits(c*b).

    Column j = bits of c * x^j (LSB-first bit order).
    """
    cols = []
    for j in range(8):
        p = int(gf_mul(c, 1 << j))
        cols.append([(p >> i) & 1 for i in range(8)])
    return np.array(cols, dtype=np.uint8).T  # columns stacked


def expand_bitmatrix(a: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix (r, s) to its GF(2) form (8r, 8s) uint8."""
    a = np.asarray(a, dtype=np.uint8)
    r, s = a.shape
    out = np.zeros((8 * r, 8 * s), dtype=np.uint8)
    for i in range(r):
        for j in range(s):
            out[8 * i : 8 * i + 8, 8 * j : 8 * j + 8] = _mul_bitmatrix(int(a[i, j]))
    return out


# ---------------------------------------------------------------------------
# Device-side bit matmul (JAX)
# ---------------------------------------------------------------------------

# jax imported lazily so host-only users (layout math, tests of table code)
# never pay for it.


def _jnp():
    import jax.numpy as jnp

    return jnp


def unpack_bits(x):
    """(..., s, n) uint8 bytes -> (..., n, 8s) int8 bits (LSB-first).

    Axis order: for byte-position p, the bit vector is the concatenation
    over the s symbols of their 8 bits — matching expand_bitmatrix.
    """
    jnp = _jnp()
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., None] >> shifts) & 1  # (..., s, n, 8)
    bits = jnp.moveaxis(bits, -3, -2)  # (..., n, s, 8)
    return bits.reshape(*bits.shape[:-2], -1).astype(jnp.int8)  # (..., n, 8s)


def pack_bits(bits, r: int):
    """(..., n, 8r) int -> (..., r, n) uint8 bytes (LSB-first)."""
    jnp = _jnp()
    bits = bits.reshape(*bits.shape[:-1], r, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    out = (bits * weights).sum(axis=-1, dtype=jnp.uint32).astype(jnp.uint8)
    return jnp.moveaxis(out, -1, -2)  # (..., r, n)


def bit_matmul_apply(bitmat_t, x):
    """Apply a GF(2^8) linear map to byte columns via one int8 MXU matmul.

    bitmat_t: (8s, 8r) int8 — expand_bitmatrix(A).T, A being (r, s).
    x:        (..., s, n) uint8 — s input symbols per byte-position.
    returns   (..., r, n) uint8 == A @ x over GF(2^8), per byte-position.

    The contraction (n, 8s) @ (8s, 8r) accumulates in int32 on the MXU;
    parity (& 1) recovers the GF(2) sum. For RS(10,4): 8s=80, 8r=32.
    """
    import jax

    jnp = _jnp()
    r8 = bitmat_t.shape[1]
    bits = unpack_bits(x)  # (..., n, 8s)
    acc = jax.lax.dot_general(
        bits,
        bitmat_t,
        dimension_numbers=(((bits.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return pack_bits(acc & 1, r8 // 8)


def bit_matmul_apply_batched(bitmats_t, x):
    """Per-item GF(2^8) linear maps in ONE batched MXU matmul — the
    pattern-as-data form of bit_matmul_apply.

    bitmats_t: (B, 8s, 8r) int8 — expand_bitmatrix(A_i).T per item.
    x:         (B, s, n) uint8 — item i's s input symbols per byte-pos.
    returns    (B, r, n) uint8 == A_i @ x_i over GF(2^8).

    Because the matrices ride as a TENSOR OPERAND instead of a trace
    constant, jit keys on shapes only: one compiled program serves
    every erasure pattern (decode/repair matrices differ per present-
    set), where the constant-matrix form compiles one XLA program per
    pattern — the unbounded-cache / recompile-per-pattern trap the
    read path's pad buckets exist to kill."""
    import jax

    jnp = _jnp()
    r8 = bitmats_t.shape[-1]
    bits = unpack_bits(x)  # (B, n, 8s)
    acc = jax.lax.dot_general(
        bits,
        bitmats_t.astype(jnp.int8),
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32,
    )  # (B, n, 8r)
    return pack_bits(acc & 1, r8 // 8)


def bitmat_t_for(a: np.ndarray):
    """Constant operand for bit_matmul_apply: expand_bitmatrix(a).T as
    int8. Returned as NUMPY on purpose: callers may be lru-cached
    builders (rs._jit_apply) whose first invocation can happen inside
    ANOTHER jit trace — a device array created there is a leaked tracer
    once the closure is cached. XLA constant-folds the numpy operand at
    trace time either way."""
    return expand_bitmatrix(a).T.astype(np.int8)
