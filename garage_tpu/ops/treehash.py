"""BLAKE3 tree hashing: pure-Python reference + batched JAX implementation.

The reference hashes every block with sequential blake2
(src/util/data.rs:124-132, verified on every read at
src/block/manager.rs:554-609) — one core, one block at a time. BLAKE3's
chunk tree is the TPU-native choice: a 1 MiB block is 1024 independent
1 KiB chunks (VPU-parallel), merged by a 10-level binary parent tree.
Scrub/verify of a whole batch of blocks becomes one jitted program.

Layout of the JAX path: messages are padded to a static chunk count C;
byte *lengths* stay traced, so one compiled program serves every block
whose size lands in the same chunk count (tail blocks don't recompile).
Within a chunk the 16 blake3 blocks chain sequentially (lax.scan); across
chunks and across the batch everything is vmapped.

The pure-Python implementation is the test oracle (checked against the
published empty-input vector) and the host fallback for small inputs.
"""

from __future__ import annotations

import functools

import numpy as np

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

MSG_PERMUTATION = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

CHUNK_START = 1 << 0
CHUNK_END = 1 << 1
PARENT = 1 << 2
ROOT = 1 << 3

CHUNK_LEN = 1024
BLOCK_LEN = 64
BLOCKS_PER_CHUNK = CHUNK_LEN // BLOCK_LEN  # 16


@functools.lru_cache(maxsize=None)
def _schedules() -> tuple[tuple[int, ...], ...]:
    """Message-word index schedule per round (permutation pre-applied)."""
    idx = list(range(16))
    out = [tuple(idx)]
    for _ in range(6):
        idx = [idx[p] for p in MSG_PERMUTATION]
        out.append(tuple(idx))
    return tuple(out)


# ---------------------------------------------------------------------------
# Pure-Python reference
# ---------------------------------------------------------------------------

_M32 = 0xFFFFFFFF


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & _M32


def _g(v, a, b, c, d, mx, my):
    v[a] = (v[a] + v[b] + mx) & _M32
    v[d] = _rotr(v[d] ^ v[a], 16)
    v[c] = (v[c] + v[d]) & _M32
    v[b] = _rotr(v[b] ^ v[c], 12)
    v[a] = (v[a] + v[b] + my) & _M32
    v[d] = _rotr(v[d] ^ v[a], 8)
    v[c] = (v[c] + v[d]) & _M32
    v[b] = _rotr(v[b] ^ v[c], 7)


def compress_py(h, m, counter: int, block_len: int, flags: int) -> list[int]:
    """One blake3 compression; returns the 8-word chaining value."""
    v = list(h) + list(IV[:4]) + [
        counter & _M32, (counter >> 32) & _M32, block_len, flags,
    ]
    for sched in _schedules():
        _g(v, 0, 4, 8, 12, m[sched[0]], m[sched[1]])
        _g(v, 1, 5, 9, 13, m[sched[2]], m[sched[3]])
        _g(v, 2, 6, 10, 14, m[sched[4]], m[sched[5]])
        _g(v, 3, 7, 11, 15, m[sched[6]], m[sched[7]])
        _g(v, 0, 5, 10, 15, m[sched[8]], m[sched[9]])
        _g(v, 1, 6, 11, 12, m[sched[10]], m[sched[11]])
        _g(v, 2, 7, 8, 13, m[sched[12]], m[sched[13]])
        _g(v, 3, 4, 9, 14, m[sched[14]], m[sched[15]])
    return [v[i] ^ v[i + 8] for i in range(8)]


def _words(block: bytes) -> list[int]:
    block = block.ljust(BLOCK_LEN, b"\x00")
    return [int.from_bytes(block[4 * i : 4 * i + 4], "little") for i in range(16)]


def _chunk_cv_py(chunk: bytes, counter: int, root: bool) -> list[int]:
    n_blocks = max(1, (len(chunk) + BLOCK_LEN - 1) // BLOCK_LEN)
    cv = list(IV)
    for b in range(n_blocks):
        piece = chunk[b * BLOCK_LEN : (b + 1) * BLOCK_LEN]
        flags = (CHUNK_START if b == 0 else 0) | (
            (CHUNK_END | (ROOT if root else 0)) if b == n_blocks - 1 else 0
        )
        cv = compress_py(cv, _words(piece), counter, len(piece), flags)
    return cv


def _parent_cv_py(left, right, root: bool) -> list[int]:
    m = list(left) + list(right)
    return compress_py(list(IV), m, 0, BLOCK_LEN, PARENT | (ROOT if root else 0))


def blake3_py(data: bytes) -> bytes:
    """Reference blake3 (default 32-byte digest)."""
    chunks = [data[i : i + CHUNK_LEN] for i in range(0, len(data), CHUNK_LEN)] or [b""]
    if len(chunks) == 1:
        cv = _chunk_cv_py(chunks[0], 0, root=True)
        return b"".join(w.to_bytes(4, "little") for w in cv)
    cvs = [_chunk_cv_py(c, i, root=False) for i, c in enumerate(chunks)]
    # Pairwise merge with odd tail carried — reproduces the spec tree
    # (left subtree = largest power of two < n) level by level.
    while len(cvs) > 2:
        nxt = [_parent_cv_py(cvs[i], cvs[i + 1], False) for i in range(0, len(cvs) - 1, 2)]
        if len(cvs) % 2:
            nxt.append(cvs[-1])
        cvs = nxt
    root = _parent_cv_py(cvs[0], cvs[1], root=True)
    return b"".join(w.to_bytes(4, "little") for w in root)


# ---------------------------------------------------------------------------
# JAX batched implementation
# ---------------------------------------------------------------------------


def _compress_jax(h, m, counter, block_len, flags):
    """h (8,) u32, m (16,) u32, scalars u32 -> (8,) u32. Fully unrolled."""
    import jax.numpy as jnp

    u32 = jnp.uint32
    v = [h[i] for i in range(8)] + [
        u32(IV[0]), u32(IV[1]), u32(IV[2]), u32(IV[3]),
        counter.astype(u32), (counter >> 32).astype(u32) if counter.dtype.itemsize == 8 else u32(0),
        block_len.astype(u32), flags.astype(u32),
    ]

    def rotr(x, n):
        return (x >> u32(n)) | (x << u32(32 - n))

    def g(a, b, c, d, mx, my):
        v[a] = v[a] + v[b] + mx
        v[d] = rotr(v[d] ^ v[a], 16)
        v[c] = v[c] + v[d]
        v[b] = rotr(v[b] ^ v[c], 12)
        v[a] = v[a] + v[b] + my
        v[d] = rotr(v[d] ^ v[a], 8)
        v[c] = v[c] + v[d]
        v[b] = rotr(v[b] ^ v[c], 7)

    for sched in _schedules():
        g(0, 4, 8, 12, m[sched[0]], m[sched[1]])
        g(1, 5, 9, 13, m[sched[2]], m[sched[3]])
        g(2, 6, 10, 14, m[sched[4]], m[sched[5]])
        g(3, 7, 11, 15, m[sched[6]], m[sched[7]])
        g(0, 5, 10, 15, m[sched[8]], m[sched[9]])
        g(1, 6, 11, 12, m[sched[10]], m[sched[11]])
        g(2, 7, 8, 13, m[sched[12]], m[sched[13]])
        g(3, 4, 9, 14, m[sched[14]], m[sched[15]])
    import jax.numpy as jnp2

    return jnp2.stack([v[i] ^ v[i + 8] for i in range(8)])


def _chunk_cv_jax(words, counter, chunk_len, is_root_chunk):
    """One chunk: words (16, 16) u32 (block, word), chunk_len u32 traced.

    lax.scan over the 16 block positions; positions past the chunk's last
    block are masked out so traced lengths don't change the program.
    """
    import jax
    import jax.numpy as jnp

    u32 = jnp.uint32
    n_blocks = jnp.maximum(u32(1), (chunk_len + u32(BLOCK_LEN - 1)) // u32(BLOCK_LEN))
    pos = jnp.arange(BLOCKS_PER_CHUNK, dtype=jnp.uint32)
    block_lens = jnp.clip(
        chunk_len.astype(jnp.int32) - (pos * BLOCK_LEN).astype(jnp.int32), 0, BLOCK_LEN
    ).astype(u32)
    is_end = pos == (n_blocks - 1)
    flags = (
        jnp.where(pos == 0, u32(CHUNK_START), u32(0))
        | jnp.where(is_end, u32(CHUNK_END), u32(0))
        | jnp.where(is_end & is_root_chunk, u32(ROOT), u32(0))
    )
    active = pos < n_blocks

    def step(cv, xs):
        m, blen, flg, act = xs
        new_cv = _compress_jax(cv, m, counter, blen, flg)
        return jnp.where(act, new_cv, cv), None

    cv, _ = jax.lax.scan(step, jnp.array(IV, dtype=u32), (words, block_lens, flags, active))
    return cv


def _parent_cv_jax(left, right, flags_val):
    import jax.numpy as jnp

    m = jnp.concatenate([left, right])
    z = jnp.uint32(0)
    return _compress_jax(
        jnp.array(IV, dtype=jnp.uint32), m, z, jnp.uint32(BLOCK_LEN), jnp.uint32(flags_val)
    )


@functools.lru_cache(maxsize=None)
def _hash_fn(n_chunks: int):
    """Jitted (B, n_chunks*1024) u8 + (B,) i32 lengths -> (B, 8) u32."""
    import jax
    import jax.numpy as jnp

    def one(msg_u8, length):
        u32 = jnp.uint32
        words = msg_u8.reshape(n_chunks, BLOCKS_PER_CHUNK, BLOCK_LEN // 4, 4)
        words = (
            words[..., 0].astype(u32)
            | (words[..., 1].astype(u32) << 8)
            | (words[..., 2].astype(u32) << 16)
            | (words[..., 3].astype(u32) << 24)
        )  # (C, 16, 16) little-endian words
        counters = jnp.arange(n_chunks, dtype=u32)
        chunk_lens = jnp.clip(length - counters.astype(jnp.int32) * CHUNK_LEN, 0, CHUNK_LEN).astype(u32)
        single = n_chunks == 1
        cvs = jax.vmap(_chunk_cv_jax, in_axes=(0, 0, 0, None))(
            words, counters, chunk_lens, jnp.bool_(single)
        )  # (C, 8)
        if single:
            return cvs[0]
        # Pairwise merge, odd tail carried (static unroll, log2 levels).
        level = [cvs[i] for i in range(n_chunks)]
        while len(level) > 2:
            nxt = [
                _parent_cv_jax(level[i], level[i + 1], PARENT)
                for i in range(0, len(level) - 1, 2)
            ]
            if len(level) % 2:
                nxt.append(level[-1])
            level = nxt
        return _parent_cv_jax(level[0], level[1], PARENT | ROOT)

    return jax.jit(jax.vmap(one))


def n_chunks_for(length: int) -> int:
    return max(1, (length + CHUNK_LEN - 1) // CHUNK_LEN)


def hash_batch_jax(msgs: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """msgs (B, C*1024) uint8 zero-padded, lengths (B,) -> (B, 32) uint8.

    All messages must share the chunk count C = msgs.shape[1] // 1024.
    """
    b, padded = msgs.shape
    if padded % CHUNK_LEN:
        raise ValueError(f"padded length {padded} not a chunk multiple")
    lengths = np.asarray(lengths, dtype=np.int32)
    c = padded // CHUNK_LEN
    if any(n_chunks_for(int(n)) != c for n in lengths):
        raise ValueError(f"all lengths must span exactly {c} chunks")
    cvs = _hash_fn(c)(msgs, lengths)
    return np.asarray(cvs).astype("<u4").view(np.uint8).reshape(b, 32)


def blake3_many(blobs: list[bytes]) -> list[bytes]:
    """Hash many byte strings, batching same-chunk-count groups on device."""
    out: list[bytes | None] = [None] * len(blobs)
    groups: dict[int, list[int]] = {}
    for i, blob in enumerate(blobs):
        groups.setdefault(n_chunks_for(len(blob)), []).append(i)
    for n_chunks, idxs in groups.items():
        padded = n_chunks * CHUNK_LEN
        buf = np.zeros((len(idxs), padded), dtype=np.uint8)
        lengths = np.empty(len(idxs), dtype=np.int32)
        for row, i in enumerate(idxs):
            arr = np.frombuffer(blobs[i], dtype=np.uint8)
            buf[row, : arr.size] = arr
            lengths[row] = arr.size
        digests = hash_batch_jax(buf, lengths)
        for row, i in enumerate(idxs):
            out[i] = digests[row].tobytes()
    return out  # type: ignore[return-value]


def blake3(data: bytes) -> bytes:
    """Single-input convenience (host reference path)."""
    return blake3_py(data)
