"""BLAKE3 tree hashing: pure-Python reference + batched JAX implementation.

The reference hashes every block with sequential blake2
(src/util/data.rs:124-132, verified on every read at
src/block/manager.rs:554-609) — one core, one block at a time. BLAKE3's
chunk tree is the TPU-native choice: a 1 MiB block is 1024 independent
1 KiB chunks (VPU-parallel), merged by a 10-level binary parent tree.
Scrub/verify of a whole batch of blocks becomes one jitted program.

Layout of the JAX path: messages are padded to a static chunk count C;
byte *lengths* stay traced, so one compiled program serves every block
whose size lands in the same chunk count (tail blocks don't recompile).
Batching is lane-major (batch = trailing vector axis, see the section
comment above _compress_lanes): all C*B chunks are lanes of one 16-step
lax.scan over block positions, and each scan step runs the 7 rounds as
an inner scan.

The pure-Python implementation is the test oracle (checked against the
published empty-input vector) and the host fallback for small inputs.
"""

from __future__ import annotations

import functools

import numpy as np

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

MSG_PERMUTATION = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

CHUNK_START = 1 << 0
CHUNK_END = 1 << 1
PARENT = 1 << 2
ROOT = 1 << 3

CHUNK_LEN = 1024
BLOCK_LEN = 64
BLOCKS_PER_CHUNK = CHUNK_LEN // BLOCK_LEN  # 16


@functools.lru_cache(maxsize=None)
def _schedules() -> tuple[tuple[int, ...], ...]:
    """Message-word index schedule per round (permutation pre-applied)."""
    idx = list(range(16))
    out = [tuple(idx)]
    for _ in range(6):
        idx = [idx[p] for p in MSG_PERMUTATION]
        out.append(tuple(idx))
    return tuple(out)


# ---------------------------------------------------------------------------
# Pure-Python reference
# ---------------------------------------------------------------------------

_M32 = 0xFFFFFFFF


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & _M32


def _g(v, a, b, c, d, mx, my):
    v[a] = (v[a] + v[b] + mx) & _M32
    v[d] = _rotr(v[d] ^ v[a], 16)
    v[c] = (v[c] + v[d]) & _M32
    v[b] = _rotr(v[b] ^ v[c], 12)
    v[a] = (v[a] + v[b] + my) & _M32
    v[d] = _rotr(v[d] ^ v[a], 8)
    v[c] = (v[c] + v[d]) & _M32
    v[b] = _rotr(v[b] ^ v[c], 7)


def compress_py(h, m, counter: int, block_len: int, flags: int) -> list[int]:
    """One blake3 compression; returns the 8-word chaining value."""
    v = list(h) + list(IV[:4]) + [
        counter & _M32, (counter >> 32) & _M32, block_len, flags,
    ]
    for sched in _schedules():
        _g(v, 0, 4, 8, 12, m[sched[0]], m[sched[1]])
        _g(v, 1, 5, 9, 13, m[sched[2]], m[sched[3]])
        _g(v, 2, 6, 10, 14, m[sched[4]], m[sched[5]])
        _g(v, 3, 7, 11, 15, m[sched[6]], m[sched[7]])
        _g(v, 0, 5, 10, 15, m[sched[8]], m[sched[9]])
        _g(v, 1, 6, 11, 12, m[sched[10]], m[sched[11]])
        _g(v, 2, 7, 8, 13, m[sched[12]], m[sched[13]])
        _g(v, 3, 4, 9, 14, m[sched[14]], m[sched[15]])
    return [v[i] ^ v[i + 8] for i in range(8)]


def _words(block: bytes) -> list[int]:
    block = block.ljust(BLOCK_LEN, b"\x00")
    return [int.from_bytes(block[4 * i : 4 * i + 4], "little") for i in range(16)]


def _chunk_cv_py(chunk: bytes, counter: int, root: bool) -> list[int]:
    n_blocks = max(1, (len(chunk) + BLOCK_LEN - 1) // BLOCK_LEN)
    cv = list(IV)
    for b in range(n_blocks):
        piece = chunk[b * BLOCK_LEN : (b + 1) * BLOCK_LEN]
        flags = (CHUNK_START if b == 0 else 0) | (
            (CHUNK_END | (ROOT if root else 0)) if b == n_blocks - 1 else 0
        )
        cv = compress_py(cv, _words(piece), counter, len(piece), flags)
    return cv


def _parent_cv_py(left, right, root: bool) -> list[int]:
    m = list(left) + list(right)
    return compress_py(list(IV), m, 0, BLOCK_LEN, PARENT | (ROOT if root else 0))


def blake3_py(data: bytes) -> bytes:
    """Reference blake3 (default 32-byte digest)."""
    chunks = [data[i : i + CHUNK_LEN] for i in range(0, len(data), CHUNK_LEN)] or [b""]
    if len(chunks) == 1:
        cv = _chunk_cv_py(chunks[0], 0, root=True)
        return b"".join(w.to_bytes(4, "little") for w in cv)
    cvs = [_chunk_cv_py(c, i, root=False) for i, c in enumerate(chunks)]
    # Pairwise merge with odd tail carried — reproduces the spec tree
    # (left subtree = largest power of two < n) level by level.
    while len(cvs) > 2:
        nxt = [_parent_cv_py(cvs[i], cvs[i + 1], False) for i in range(0, len(cvs) - 1, 2)]
        if len(cvs) % 2:
            nxt.append(cvs[-1])
        cvs = nxt
    root = _parent_cv_py(cvs[0], cvs[1], root=True)
    return b"".join(w.to_bytes(4, "little") for w in root)


# ---------------------------------------------------------------------------
# JAX batched implementation — lane-major
# ---------------------------------------------------------------------------
#
# Batch layout: every independent hash unit (chunk of a row, then parent
# node of a tree level) is a *lane* — the trailing axis of every array.
# State is (8, L), messages (16, L); the compression function is ~450
# elementwise u32 ops on (L,) vectors regardless of batch size, so the
# HLO graph is batch-size independent (a vmap formulation made XLA:CPU
# compile time explode superlinearly in B) and maps straight onto the
# TPU VPU's 128-wide lanes.


def _compress_lanes(h, m, counter, block_len, flags):
    """h (8, L), m (16, L), counter/block_len/flags (L,) or scalar u32
    -> (8, L). All ops lane-vectorized.

    The 7 rounds run as a lax.scan whose body gathers that round's
    message schedule — keeping the HLO body near 70 ops. A fully
    unrolled formulation (~450 interdependent u32 ops) sends XLA:CPU's
    backend into multi-minute compiles for any lane count >= 4; the
    scan form compiles in seconds everywhere and XLA still unrolls or
    pipelines it on TPU as it sees fit.
    """
    import jax
    import jax.numpy as jnp

    u32 = jnp.uint32
    ones = jnp.ones_like(h[0])
    sched = jnp.asarray(np.array(_schedules(), dtype=np.int32))  # (7, 16)
    v0 = jnp.stack(
        [h[i] for i in range(8)]
        + [
            u32(IV[0]) * ones, u32(IV[1]) * ones, u32(IV[2]) * ones, u32(IV[3]) * ones,
            counter * ones, jnp.zeros_like(ones),
            block_len * ones, flags * ones,
        ]
    )  # (16, L)

    def rotr(x, n):
        return (x >> u32(n)) | (x << u32(32 - n))

    def round_body(vs, idx):
        mr = jnp.take(m, idx, axis=0)  # (16, L) permuted message
        v = [vs[i] for i in range(16)]

        def g(a, b, c, d, mx, my):
            v[a] = v[a] + v[b] + mx
            v[d] = rotr(v[d] ^ v[a], 16)
            v[c] = v[c] + v[d]
            v[b] = rotr(v[b] ^ v[c], 12)
            v[a] = v[a] + v[b] + my
            v[d] = rotr(v[d] ^ v[a], 8)
            v[c] = v[c] + v[d]
            v[b] = rotr(v[b] ^ v[c], 7)

        g(0, 4, 8, 12, mr[0], mr[1])
        g(1, 5, 9, 13, mr[2], mr[3])
        g(2, 6, 10, 14, mr[4], mr[5])
        g(3, 7, 11, 15, mr[6], mr[7])
        g(0, 5, 10, 15, mr[8], mr[9])
        g(1, 6, 11, 12, mr[10], mr[11])
        g(2, 7, 8, 13, mr[12], mr[13])
        g(3, 4, 9, 14, mr[14], mr[15])
        return jnp.stack(v), None

    v, _ = jax.lax.scan(round_body, v0, sched)
    return v[:8] ^ v[8:16]


def hash_rows(msgs, lengths, n_chunks: int):
    """Traceable batched hash: (B, n_chunks*1024) u8 + (B,) i32 -> (B, 8) u32.

    Precondition (caller-enforced, like hash_batch_jax does): every row's
    length must span exactly n_chunks, i.e. n_chunks_for(length) ==
    n_chunks, and bytes past `length` must be zero — otherwise the digest
    is silently wrong (phantom all-zero chunks enter the tree).

    Composable inside larger jitted programs (parallel/ data-plane steps);
    _hash_fn below is the standalone jitted wrapper. All C*B chunks hash
    as lanes of one 16-step lax.scan over block positions; the parent
    tree is a static log2(C) unroll, each level one lane-vectorized
    compression over all pairs of all rows.
    """
    import jax
    import jax.numpy as jnp

    u32 = jnp.uint32
    b = msgs.shape[0]
    c = n_chunks
    w = msgs.reshape(b, c, BLOCKS_PER_CHUNK, BLOCK_LEN // 4, 4).astype(u32)
    words = w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)
    # (B, C, block, word) -> (block, word, C*B) lane = chunk-major
    words = words.transpose(2, 3, 1, 0).reshape(BLOCKS_PER_CHUNK, 16, c * b)

    counters = jnp.repeat(jnp.arange(c, dtype=u32), b)  # (C*B,)
    chunk_lens = jnp.clip(
        lengths[None, :] - jnp.arange(c, dtype=jnp.int32)[:, None] * CHUNK_LEN,
        0,
        CHUNK_LEN,
    ).astype(u32).reshape(c * b)
    n_blocks = jnp.maximum(u32(1), (chunk_lens + u32(BLOCK_LEN - 1)) // u32(BLOCK_LEN))

    pos = jnp.arange(BLOCKS_PER_CHUNK, dtype=u32)[:, None]  # (block, 1)
    block_lens = jnp.clip(
        chunk_lens[None, :].astype(jnp.int32) - (pos * BLOCK_LEN).astype(jnp.int32),
        0,
        BLOCK_LEN,
    ).astype(u32)  # (block, C*B)
    is_end = pos == (n_blocks - 1)[None, :]
    root_if_single = u32(ROOT if c == 1 else 0)
    flags = (
        jnp.where(pos == 0, u32(CHUNK_START), u32(0))
        | jnp.where(is_end, u32(CHUNK_END) | root_if_single, u32(0))
    )
    active = pos < n_blocks[None, :]

    def step(cv, xs):
        m, blen, flg, act = xs
        new_cv = _compress_lanes(cv, m, counters, blen, flg)
        return jnp.where(act, new_cv, cv), None

    init = jnp.tile(jnp.array(IV, dtype=u32)[:, None], (1, c * b))
    cv, _ = jax.lax.scan(step, init, (words, block_lens, flags, active))  # (8, C*B)

    if c == 1:
        return cv.T  # (B, 8)

    # Parent tree: pairwise merge with odd tail carried, all rows' pairs
    # in lanes of one compression per level.
    level = [cv.reshape(8, c, b)[:, i, :] for i in range(c)]  # C x (8, B)
    zero = u32(0)

    def merge(pairs_l, pairs_r, flags_val):
        ln = len(pairs_l)
        left = jnp.concatenate(pairs_l, axis=-1)  # (8, ln*B)
        right = jnp.concatenate(pairs_r, axis=-1)
        m = jnp.concatenate([left, right], axis=0)  # (16, ln*B)
        iv = jnp.tile(jnp.array(IV, dtype=u32)[:, None], (1, ln * b))
        out = _compress_lanes(iv, m, zero, u32(BLOCK_LEN), u32(flags_val))
        return [out[:, i * b : (i + 1) * b] for i in range(ln)]

    while len(level) > 2:
        nxt = merge(level[0:-1:2], level[1::2], PARENT)
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    (root,) = merge([level[0]], [level[1]], PARENT | ROOT)
    return root.T  # (B, 8)


@functools.lru_cache(maxsize=None)
def _hash_fn(n_chunks: int):
    """Jitted (B, n_chunks*1024) u8 + (B,) i32 lengths -> (B, 8) u32."""
    import jax

    return jax.jit(functools.partial(hash_rows, n_chunks=n_chunks))


def hash_fn(n_chunks: int):
    """Public handle on the per-chunk-count jitted hasher: the staged
    device backend (block/device_backend.py) launches it in its compute
    stage and reads the result back in a separate d2h stage, so the two
    can overlap across pipelined batches (hash_batch_jax fuses launch
    and readback, which serializes the pipeline)."""
    return _hash_fn(n_chunks)


def n_chunks_for(length: int) -> int:
    return max(1, (length + CHUNK_LEN - 1) // CHUNK_LEN)


def hash_batch_jax(msgs: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """msgs (B, C*1024) uint8 zero-padded, lengths (B,) -> (B, 32) uint8.

    All messages must share the chunk count C = msgs.shape[1] // 1024.
    """
    b, padded = msgs.shape
    if padded % CHUNK_LEN:
        raise ValueError(f"padded length {padded} not a chunk multiple")
    lengths = np.asarray(lengths, dtype=np.int32)
    c = padded // CHUNK_LEN
    if any(n_chunks_for(int(n)) != c for n in lengths):
        raise ValueError(f"all lengths must span exactly {c} chunks")
    cvs = _hash_fn(c)(msgs, lengths)
    # ascontiguousarray: device transfers can return a transposed layout
    # whose last axis is not contiguous, which .view(uint8) rejects
    out = np.ascontiguousarray(np.asarray(cvs).astype("<u4"))
    return out.view(np.uint8).reshape(b, 32)


def blake3_many(blobs: list[bytes]) -> list[bytes]:
    """Hash many byte strings, batching same-chunk-count groups on device."""
    out: list[bytes | None] = [None] * len(blobs)
    groups: dict[int, list[int]] = {}
    for i, blob in enumerate(blobs):
        groups.setdefault(n_chunks_for(len(blob)), []).append(i)
    for n_chunks, idxs in groups.items():
        padded = n_chunks * CHUNK_LEN
        buf = np.zeros((len(idxs), padded), dtype=np.uint8)
        lengths = np.empty(len(idxs), dtype=np.int32)
        for row, i in enumerate(idxs):
            arr = np.frombuffer(blobs[i], dtype=np.uint8)
            buf[row, : arr.size] = arr
            lengths[row] = arr.size
        digests = hash_batch_jax(buf, lengths)
        for row, i in enumerate(idxs):
            out[i] = digests[row].tobytes()
    return out  # type: ignore[return-value]


def blake3(data: bytes) -> bytes:
    """Single-input convenience (host reference path)."""
    return blake3_py(data)
