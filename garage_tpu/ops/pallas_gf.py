"""Pallas TPU kernel for GF(2^8) matrix application (RS encode/decode).

The XLA path (gf256.bit_matmul_apply) materializes the 8x bit expansion
in HBM: a 1 MiB block becomes 8 MiB of int8 bit-planes before the
matmul, and the packed result round-trips again — HBM traffic is ~9x
the payload. This kernel fuses unpack -> matmul -> parity -> pack
inside VMEM, so HBM sees only the raw bytes in (k rows) and out
(m rows) per tile.

Layout per grid step (b, s):
  data tile  (k, T) u8   -> bits (8k, T) i8 (bit j of symbol s at row
                            s*8+j, matching gf256.expand_bitmatrix)
  bitmat     (8m, 8k) i8 (constant, VMEM-resident)
  acc        (8m, T) i32 = bitmat @ bits   [MXU]
  parity     (m, T) u8   = pack(acc & 1)

Used on real TPU backends only; CPU tests run it in interpreter mode
(see tests/test_rs.py) and the production fallback is the XLA path.
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf256

LANE_TILE = 2048  # bytes of each shard processed per grid step


def _kernel(mat_ref, x_ref, o_ref, *, k: int, m: int):
    """Mosaic-friendly formulation: no narrow-dtype 3-D intermediates.
    Bit rows are built by concatenating 8 shifted copies along the
    sublane axis (row order j*k + s); the COLUMN permutation that maps
    this order back to the canonical s*8 + j layout is pre-applied to
    the constant matrix on the host (_mat_bits_jk)."""
    import jax
    import jax.numpy as jnp

    x = x_ref[...].astype(jnp.int32)  # (k, T)
    # f32 matmul: this backend's Mosaic AOT path rejects int-typed
    # dot_general; sums are <= 8k <= 2048 so f32 is exact
    bits = jnp.concatenate(
        [((x >> j) & 1).astype(jnp.float32) for j in range(8)],
        axis=0)  # (8k, T), row j*k+s
    acc = jax.lax.dot_general(
        mat_ref[...].astype(jnp.float32), bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (8m, T), row i*8 + bit
    t = x.shape[1]
    # pack: weight each bit row by 1 << (row % 8), sum groups of 8 rows
    row_w = jnp.tile(1 << jnp.arange(8, dtype=jnp.int32), m)[:, None]
    weighted = ((acc.astype(jnp.int32) & 1) * row_w).reshape(m, 8, t)
    o_ref[...] = weighted.sum(axis=1).astype(jnp.uint8)


@functools.lru_cache(maxsize=None)
def _build(k: int, rows: int, shard_len: int, batch: int,
           interpret: bool):
    """Jitted pallas_call applying an (rows x k) GF matrix to
    (batch, k, shard_len) uint8."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    for tile in (LANE_TILE, 1024, 512, 256, 128):
        if tile <= shard_len and shard_len % tile == 0:
            break
    else:
        raise ValueError(f"shard_len {shard_len} has no lane tile")
    grid = (batch, shard_len // tile)

    call = pl.pallas_call(
        functools.partial(_kernel, k=k, m=rows),
        out_shape=jax.ShapeDtypeStruct((batch, rows, shard_len),
                                       jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * rows, 8 * k), lambda b, s: (0, 0)),
            pl.BlockSpec((None, k, tile), lambda b, s: (b, 0, s)),
        ],
        out_specs=pl.BlockSpec((None, rows, tile), lambda b, s: (b, 0, s)),
        interpret=interpret,
    )

    @jax.jit
    def apply(mat_bits, x):
        return call(mat_bits, x)

    return apply


@functools.lru_cache(maxsize=None)
def _mat_bits_jk(mat_bytes: bytes, rows: int, k: int) -> np.ndarray:
    """expand_bitmatrix with columns permuted from canonical s*8+j to
    the kernel's concatenation order j*k+s."""
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(rows, k)
    exp = gf256.expand_bitmatrix(mat)  # (8r, 8k), col s*8+j
    perm = np.empty(8 * k, dtype=np.int64)
    for j in range(8):
        for s in range(k):
            perm[j * k + s] = s * 8 + j
    return np.ascontiguousarray(exp[:, perm]).astype(np.int8)


def gf_apply(mat: np.ndarray, data, interpret: bool = False):
    """Apply a GF(2^8) matrix (rows, k) to data (B, k, S) uint8 ->
    (B, rows, S) uint8 on device via the fused Pallas kernel."""
    import jax.numpy as jnp

    rows, k = mat.shape
    b, k2, s = data.shape
    if k2 != k:
        raise ValueError(f"matrix {mat.shape} does not match data "
                         f"{data.shape}")
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    mat_bits = jnp.asarray(_mat_bits_jk(mat.tobytes(), rows, k))
    fn = _build(k, rows, s, b, interpret)
    return fn(mat_bits, data)


def encode(k: int, m: int, data, interpret: bool = False):
    """RS parity via the Pallas kernel: (B, k, S) -> (B, m, S)."""
    from . import rs

    return gf_apply(rs.parity_matrix(k, m), data, interpret=interpret)


def available() -> bool:
    """Pallas TPU kernels need a real TPU backend."""
    try:
        import jax

        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
