"""Static website server: serves buckets over vhost-style domains.

Ref parity: src/web/web_server.rs. See server.WebServer.
"""

from .server import WebServer, path_to_keys

__all__ = ["WebServer", "path_to_keys"]
