"""WebServer: anonymous static-website serving of buckets.

Ref parity: src/web/web_server.rs:70-450. Requests address a bucket by
vhost (`{bucket}.{web_root_domain}` — or any alias/custom domain that
resolves as a global bucket alias). The bucket must have a website
configuration; GET/HEAD reuse the S3 object read path without
authentication, OPTIONS evaluates the bucket's CORS rules, and errors
render the configured error document. Folder-style paths follow the
S3 website rules (web_server.rs:420-447 path_to_keys): a trailing slash
serves `{path}{index}`, no trailing slash 302-redirects to `{path}/`
when `{path}/{index}` exists.
"""

from __future__ import annotations

import logging
from typing import Optional
from urllib.parse import unquote

from ..api.http import HttpServer, Request, Response
from ..api.s3 import get as get_handlers
from ..api.s3 import website as website_handlers
from ..api.s3.api_server import ReqCtx
from ..api.s3.xml import S3Error
from ..model.helper import GarageHelper

log = logging.getLogger("garage_tpu.web")


def path_to_keys(path: str, index: str) -> tuple[str, Optional[tuple[str, str]]]:
    """-> (key to serve, implicit redirect (key, url) or None).
    ref: web_server.rs:420-447."""
    decoded = unquote(path)
    if not decoded.startswith("/"):
        raise S3Error("InvalidRequest", 400, "path must start with /")
    base_key = decoded[1:]
    if not base_key:
        return index, None
    if decoded.endswith("/"):
        return base_key + index, None
    return base_key, (f"{base_key}/{index}", f"{path}/")


class WebServer:
    def __init__(self, garage, s3_server=None,
                 root_domain: Optional[str] = None):
        self.garage = garage
        self.helper = GarageHelper(garage)
        self.root_domain = root_domain or garage.config.web_root_domain
        self.http = HttpServer(self.handle, name="web")
        self.metrics = {"requests": 0, "errors": 0}

    async def start(self, host: str, port=None,
                    reuse_port: bool = False) -> None:
        # a path (port None) binds a Unix-domain socket, like the
        # reference's UnixOrTCPSocketAddress bind addresses; reuse_port
        # is the gateway workers' SO_REUSEPORT shared accept loop
        if port is None:
            await self.http.start_unix(host)
        else:
            await self.http.start(host, port, reuse_port=reuse_port)

    async def stop(self) -> None:
        await self.http.stop()

    def _bucket_name(self, req: Request) -> str:
        host = (req.header("host") or "").split(":")[0].lower()
        if not host:
            raise S3Error("InvalidRequest", 400, "Host header required")
        if host.endswith(self.root_domain):
            return host[: -len(self.root_domain)]
        return host  # custom domain == global alias (ref: host_to_bucket)

    async def handle(self, req: Request) -> Response:
        self.metrics["requests"] += 1
        try:
            return await self._serve(req)
        except S3Error as e:
            self.metrics["errors"] += 1
            return e.response()

    async def _serve(self, req: Request) -> Response:
        bucket_name = self._bucket_name(req)
        bucket_id = await self.helper.resolve_global_bucket_name(bucket_name)
        if bucket_id is None:
            raise S3Error("NoSuchBucket", 404, bucket_name)
        bucket = await self.helper.get_existing_bucket(bucket_id)
        params = bucket.params
        website = params.website_config.value
        if website is None:
            raise S3Error("NoSuchWebsiteConfiguration", 404,
                          "Bucket is not configured for website hosting")
        index = website.get("index_document") or "index.html"

        if req.method == "OPTIONS":
            return website_handlers.handle_options_for_bucket(req, params)
        if req.method not in ("GET", "HEAD"):
            raise S3Error("MethodNotAllowed", 405,
                          "HTTP method not supported on websites")

        # raw_path: the key comes from percent-decoding the original
        # path; the redirect URL reuses the still-encoded form
        key, may_redirect = path_to_keys(req.raw_path, index)
        ctx = ReqCtx(self.garage, bucket_id, bucket_name, bucket, key,
                     None, None)
        try:
            resp = await get_handlers.handle_get(ctx, req,
                                                 head=req.method == "HEAD")
            for n, v in resp.headers:
                if n == "x-amz-website-redirect-location":
                    # object-level redirect (ref: web_server.rs:302-309)
                    resp = Response(301, [("location", v)])
                    break
        except S3Error as e:
            if e.code == "NoSuchKey" and may_redirect is not None:
                redirect_key, url = may_redirect
                if await self._key_exists(bucket_id, redirect_key):
                    return Response(302, [("location", url)])
            resp = await self._error_response(req, ctx, website, e)
        return website_handlers.apply_cors_to_response(req, params, resp)

    async def _key_exists(self, bucket_id: bytes, key: str) -> bool:
        obj = await self.garage.object_table.get(bucket_id, key.encode())
        return obj is not None and obj.last_data() is not None

    async def _error_response(self, req: Request, ctx: ReqCtx, website: dict,
                              err: S3Error) -> Response:
        """Render the configured error document for 4xx GETs
        (ref: web_server.rs:330-390)."""
        error_doc = website.get("error_document")
        if (req.method == "HEAD" or not error_doc
                or not 400 <= err.status < 500):
            raise err
        ctx2 = ReqCtx(ctx.garage, ctx.bucket_id, ctx.bucket_name,
                      ctx.bucket, error_doc.lstrip("/"), None, None)
        try:
            doc = await get_handlers.handle_get(ctx2, req)
        except S3Error:
            raise err
        # serve the error document body with the ORIGINAL error status
        doc.status = err.status
        return doc
