"""BudgetLease broker: rents each gateway worker a share of the node's
qos budgets (req/s and bytes/s) and rebalances by observed demand.

Conservation is the contract: **Σ granted ≤ budget at all times**,
including mid-rebalance and across worker death. It holds by
construction, not by periodic correction:

  * a lease only GROWS out of `pool_free` (budget minus everything
    currently granted) at the moment the owning worker renews;
  * a lease SHRINKS the instant its owner renews (the worker applies
    the smaller rate before the broker hands the difference to anyone
    else — renew() is the only place a grant changes, and the returned
    Lease is what the worker enforces);
  * death / TTL expiry returns the whole grant to the pool — a dead
    worker is not admitting, so the budget is genuinely free.

Rebalance law: each dimension's desired share is a demand-proportional
split of the budget above a per-worker floor (`min_share` of the fair
share). The floor is the demand-discovery mechanism: an idle worker
keeps a trickle leased so the first burst it receives is admitted and
shows up as demand, which the next renew converts into real budget.
Convergence therefore takes ~2 renew rounds: one for the cold workers
to shrink to their floor (freeing pool), one for the hot worker to
absorb the freed budget.

Demand smoothing (EWMA) lives in the broker, not the workers: workers
report raw observed rates and the broker owns the time constant, so a
worker restart cannot reset the signal. The broker is deliberately
synchronous and clock-injected — the same object is driven by the
supervisor's RPC handler in production and by a fake clock in tests —
and is the piece cluster-wide distributed rate limiting will lift
verbatim (each NODE then leases from a gossiped global budget the way
each worker leases from the node budget here).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils import sanitizer

# EWMA weight for one demand sample (per renew interval)
DEMAND_ALPHA = 0.3


@dataclass
class Lease:
    """One worker's current rental. `None` rates mean that dimension is
    unlimited (no node budget configured)."""

    worker: str
    rps: Optional[float]
    bytes_per_s: Optional[float]
    seq: int
    ttl_s: float

    def to_dict(self) -> dict:
        return {"worker": self.worker, "rps": self.rps,
                "bytes_per_s": self.bytes_per_s, "seq": self.seq,
                "ttl_s": self.ttl_s}


class _Dimension:
    """Per-dimension (rps / bytes) grant ledger."""

    def __init__(self, total: Optional[float]):
        self.total = total
        self.granted: dict[str, float] = {}
        self.demand: dict[str, float] = {}

    def drop(self, worker: str) -> None:
        self.granted.pop(worker, None)
        self.demand.pop(worker, None)

    def observe(self, worker: str, sample: float) -> None:
        prev = self.demand.get(worker)
        self.demand[worker] = (max(0.0, sample) if prev is None else
                               prev + DEMAND_ALPHA * (sample - prev))

    def renew(self, worker: str, min_share: float,
              expected: int) -> Optional[float]:
        if self.total is None:
            self.granted.pop(worker, None)
            return None
        live = set(self.granted) | {worker}
        n = max(len(live), expected, 1)
        fair = self.total / n
        floor = min(fair, min_share * fair)
        spread = self.total - n * floor
        dsum = sum(self.demand.get(w, 0.0) for w in live)
        if dsum > 0:
            desired = floor + spread * self.demand.get(worker, 0.0) / dsum
        else:
            desired = fair
        cur = self.granted.get(worker, 0.0)
        if desired <= cur:
            # shrink applies NOW: the worker sees the smaller rate in
            # this renew's reply, before the freed budget can be
            # re-granted to anyone else
            grant = desired
        else:
            # growth only out of what is provably unallocated
            pool_free = self.total - sum(self.granted.values())
            grant = min(desired, cur + max(0.0, pool_free))
        self.granted[worker] = grant
        return grant

    @property
    def conservation_ok(self) -> bool:
        if self.total is None:
            return True
        # float-tolerant: grants are sums of budget fractions.
        # tuple() first: read from the /metrics scrape THREAD while
        # renews mutate on the loop — the C-level copy is atomic under
        # the GIL, a Python-level iteration is not
        return sum(tuple(self.granted.values())) \
            <= self.total * (1 + 1e-9)


class BudgetLeaseBroker:
    def __init__(self, total_rps: Optional[float] = None,
                 total_bytes_per_s: Optional[float] = None, *,
                 min_share: float = 0.05, ttl_s: float = 3.0,
                 expected_workers: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.min_share = min_share
        self.ttl_s = ttl_s
        self.expected_workers = max(1, int(expected_workers))
        self._rps = _Dimension(total_rps)
        self._bps = _Dimension(total_bytes_per_s)
        self._expiry: dict[str, float] = {}
        self._seq = 0
        self.renews = 0
        self.revokes = 0
        self.expiries = 0
        # Σ leases ≤ budget re-checked at every loop teardown under
        # GARAGE_SANITIZE=1 (no-op when disarmed)
        sanitizer.track_conservation(self)

    # ---- configuration -------------------------------------------------

    def set_totals(self, rps: Optional[float] = ...,
                   bytes_per_s: Optional[float] = ...) -> None:
        """Runtime budget change (admin POST /v1/qos). A raised budget
        is handed out as workers renew; a lowered one is reclaimed
        shrink-first (renew() never grows a grant while Σ exceeds the
        new total, because pool_free is negative)."""
        if rps is not ...:
            self._rps.total = rps
        if bytes_per_s is not ...:
            self._bps.total = bytes_per_s

    # ---- lease lifecycle -----------------------------------------------

    def renew(self, worker: str, demand_rps: float = 0.0,
              demand_bytes_per_s: float = 0.0) -> Lease:
        """Grant/refresh `worker`'s lease. Also serves as join (first
        renew) — there is deliberately no separate acquire verb."""
        self.expire()
        self._rps.observe(worker, demand_rps)
        self._bps.observe(worker, demand_bytes_per_s)
        rps = self._rps.renew(worker, self.min_share,
                              self.expected_workers)
        bps = self._bps.renew(worker, self.min_share,
                              self.expected_workers)
        self._expiry[worker] = self.clock() + self.ttl_s
        self._seq += 1
        self.renews += 1
        return Lease(worker, rps, bps, self._seq, self.ttl_s)

    def revoke(self, worker: str) -> None:
        """Worker death: the grant drains straight back to the pool (a
        dead process admits nothing, so the budget is really free)."""
        if worker in self._expiry or worker in self._rps.granted \
                or worker in self._bps.granted:
            self.revokes += 1
        self._rps.drop(worker)
        self._bps.drop(worker)
        self._expiry.pop(worker, None)

    def expire(self) -> list[str]:
        """Reclaim leases whose owner went silent past the TTL (hung
        worker: its loop is not admitting either, symmetrical with
        revoke). Called on every renew and by the supervisor monitor."""
        now = self.clock()
        dead = [w for w, t in self._expiry.items() if t < now]
        for w in dead:
            self._rps.drop(w)
            self._bps.drop(w)
            del self._expiry[w]
            self.expiries += 1
        return dead

    # ---- surface -------------------------------------------------------

    @property
    def conservation_ok(self) -> bool:
        return self._rps.conservation_ok and self._bps.conservation_ok

    def granted(self, worker: str) -> tuple[Optional[float],
                                            Optional[float]]:
        return (self._rps.granted.get(worker),
                self._bps.granted.get(worker))

    def state(self) -> dict:
        def dim(d: _Dimension) -> dict:
            # dict() snapshots are GIL-atomic: state() is read from the
            # /metrics scrape thread while renew/revoke mutate the live
            # dicts on the event loop
            granted = dict(d.granted)
            demand = dict(d.demand)
            return {
                "total": d.total,
                "granted": {w: round(v, 3) for w, v in granted.items()},
                "demand": {w: round(v, 3) for w, v in demand.items()},
                "pool_free": (None if d.total is None else
                              round(d.total - sum(granted.values()), 3)),
            }

        return {
            "rps": dim(self._rps), "bytes_per_s": dim(self._bps),
            "ttl_s": self.ttl_s, "min_share": self.min_share,
            "expected_workers": self.expected_workers,
            "conservation_ok": self.conservation_ok,
            "renews": self.renews, "revokes": self.revokes,
            "expiries": self.expiries,
        }
