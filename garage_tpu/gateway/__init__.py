"""Multi-process S3/K2V/web gateway (ISSUE 8; no reference analogue).

One asyncio loop plus the GIL caps a node's frontend throughput
regardless of how fast the data plane underneath it is (BENCH_r05:
s3_put 0.16 GB/s vs internal put 0.36 GB/s vs host RS encode
1.56 GB/s). The standard answer is shared-nothing per-core frontends
(Seastar/ScyllaDB thread-per-core; nginx/Envoy `SO_REUSEPORT` worker
processes), and that is what this package builds:

  * `supervisor.py` — runs inside the store node process. Forks N
    worker processes, respawns crashed ones (rate-limited), brokers
    qos budget leases, aggregates per-worker /metrics under a `worker`
    label and fans runtime-tuning writes out to every worker.
  * `worker.py` — the worker process entry point. Each worker is an
    API-only Garage node (no capacity, memory metadata engine) that
    binds the S3/K2V/web ports with SO_REUSEPORT — the kernel balances
    accepts across workers — and talks to the store node over the
    existing loopback `net/` RPC transport.
  * `lease.py` — `BudgetLeaseBroker`: rents each worker a share of the
    node's req/s + bytes/s budgets and rebalances by observed demand,
    holding Σ(leases) ≤ budget at every instant. The same lease
    protocol cluster-wide distributed rate limiting needs (ROADMAP).
  * `ring.py` — rendezvous-hash ownership of cacheable block hashes
    across workers, so the node holds one decoded copy per hot block
    instead of N.

`[gateway] workers = 1` (the default) keeps the single-process
frontends exactly as before; `0` means auto(cpu_count).
"""

from .lease import BudgetLeaseBroker, Lease  # noqa: F401
from .ring import CacheRing  # noqa: F401

GATEWAY_RPC_PATH = "garage_tpu/gateway"
