"""Zero-copy intra-node cache forwards (ISSUE 15): a shared-memory
segment ring replacing the loopback-socket payload copy.

PR 8's worker-sharded cache forwards every MiB-scale payload over a
loopback socket: the owner worker serializes it, the kernel copies it
twice, the forwarding worker deserializes it — three-plus copies per
forward of bytes that already sit in the owner's page-addressable RAM.
This module makes the payload cross the process boundary through one
mmap'd file instead:

  * The OWNER worker keeps one `ShmRing` — a file in /dev/shm (tmpfs;
    falls back to the metadata dir when absent), mmap'd, carved into a
    circular log of variable-size slots. `publish(hash, payload)`
    writes the payload ONCE and returns a tiny reference
    {path, off, seq, len} that rides the RPC reply instead of the
    bytes. A hash already published reuses its live slot — a hot block
    is written once per lease, not once per forward.
  * The FORWARDING worker keeps a `ShmReader` — a cache of mmaps keyed
    by ring path. `get(ref)` validates the slot header (magic, seq,
    hash, length) and returns a memoryview over the mapped payload:
    the bytes go from the owner's one write straight into the HTTP
    response (PR 2's zero-copy write path slices memoryviews natively).

Safety protocol: the reference only exists AFTER publish() returned,
and the RPC round trip orders the reply after the write — a reader can
never see a torn slot at serve-start. The remaining hazard is REUSE
while a slow client still streams the mapped bytes; the ring never
rewrites a slot before its lease (`[gateway] shm_lease_s`, default
60 s) expires, and when the ring cannot host a payload without
breaking that promise, publish() returns None and the forward falls
back to the socket path (`cache_tier_shm_fallback` counts how often).
`[gateway] shm_forwards = false` is the kill switch: no ring is
created and every forward carries bytes over the socket as before.

Ring files are keyed by (cluster metadata dir, worker index), so a
respawned worker reopens the SAME inode its siblings already map —
their existing mmaps keep working — and stale references from the
previous incarnation fail the seq check (seqs start from a fresh
random epoch) instead of serving garbage.
"""

from __future__ import annotations

import hashlib
import logging
import mmap
import os
import struct
import threading
import time
from collections import deque
from typing import Optional

log = logging.getLogger("garage_tpu.gateway.shm")

MAGIC = b"GTSM"
# magic(4) pad(4) seq(8) length(8) hash(32) = 56, padded to 64
HEADER = struct.Struct("<4s4xQQ32s")
SLOT_ALIGN = 64
HEADER_SIZE = 64
# payloads below this aren't worth a second mmap lookup on the reader
# side; the socket copy of a few KiB costs less than it saves
SHM_MIN_BYTES = 64 * 1024


def ring_path(metadata_dir: str, index: int) -> str:
    """Stable per-(cluster, worker) ring path: respawns reuse the same
    inode, parallel test clusters never collide."""
    tag = hashlib.blake2b(os.path.abspath(metadata_dir).encode(),
                          digest_size=8).hexdigest()
    base = "/dev/shm" if os.path.isdir("/dev/shm") else metadata_dir
    return os.path.join(base, f"garage-gw-{tag}-w{index}.ring")


class ShmRing:
    """Owner-side publisher: bump-pointer circular log with leased,
    never-rewritten-early slots."""

    def __init__(self, path: str, size: int, lease_s: float = 60.0):
        self.path = path
        self.size = max(int(size), HEADER_SIZE * 16)
        self.lease_s = float(lease_s)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # O_CREAT without O_TRUNC: a respawned owner reuses the inode
        # its siblings already map (ftruncate to the same size is a
        # no-op on contents)
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            fresh = os.fstat(fd).st_size != self.size
            os.ftruncate(fd, self.size)
            self._mm = mmap.mmap(fd, self.size)
        finally:
            os.close(fd)
        if fresh:
            # prefault a FRESH ring once (one boot-time memset):
            # without this every first-touch publish pays a page fault
            # per 4 KiB, which measured SLOWER than the socket copy it
            # replaces. A crash-respawn reopening the existing inode
            # must NOT do this — siblings may still be streaming leased
            # slots out of their mappings, and zeroing would corrupt
            # those in-flight responses (the pages are already resident
            # from the previous incarnation anyway).
            self._mm[:] = bytes(self.size)
        # seq epoch: random per incarnation so references minted by a
        # previous process life can never validate against new content
        self._seq = int.from_bytes(os.urandom(6), "big") << 16
        self._head = 0  # next write offset
        # oldest-first records of live slots: (off, total_len, seq,
        # lease_deadline_monotonic)
        self._live: deque = deque()
        # hash -> (off, payload_len, seq, deadline): a hot block is
        # written once per lease window, not once per forward
        self._by_hash: dict[bytes, tuple] = {}
        self._lock = threading.Lock()
        self.published = 0
        self.reused = 0
        self.fallbacks = 0

    def _expire(self, now: float) -> None:
        while self._live and self._live[0][3] <= now:
            self._live.popleft()
        # prune hash-index entries whose slot expired (amortized: only
        # when the index clearly outgrew the live set)
        if len(self._by_hash) > 4 * len(self._live) + 16:
            live_seqs = {s for _o, _n, s, _d in self._live}
            self._by_hash = {h: v for h, v in self._by_hash.items()
                             if v[2] in live_seqs}

    def publish(self, hash32: bytes, payload) -> Optional[dict]:
        """Write `payload` into the ring; -> reference dict or None
        when the ring cannot host it without rewriting a leased slot
        (caller falls back to the socket)."""
        mv = memoryview(payload)
        n = mv.nbytes
        total = HEADER_SIZE + n
        total += (-total) % SLOT_ALIGN
        if total > self.size:
            self.fallbacks += 1
            return None
        now = time.monotonic()
        with self._lock:
            self._expire(now)
            hit = self._by_hash.get(hash32)
            if hit is not None:
                off, plen, seq, deadline = hit
                if deadline > now and plen == n:
                    self.reused += 1
                    return {"path": self.path, "off": off, "seq": seq,
                            "len": n}
            off = self._allocate(total, now)
            if off is None:
                self.fallbacks += 1
                return None
            seq = self._seq = self._seq + 1
            deadline = now + self.lease_s
            self._mm[off + HEADER_SIZE:off + HEADER_SIZE + n] = mv
            self._mm[off:off + HEADER.size] = HEADER.pack(
                MAGIC, seq, n, bytes(hash32))
            self._live.append((off, total, seq, deadline))
            self._by_hash[hash32] = (off, n, seq, deadline)
            self.published += 1
            return {"path": self.path, "off": off, "seq": seq, "len": n}

    def _allocate(self, total: int, now: float) -> Optional[int]:
        """Bump-pointer allocation that never overwrites a leased slot.
        Slots are written in ring order, so the live region is at most
        two runs — [tail, size) from before the last wrap and [0, head)
        after it — and the free space is exactly the gap from head
        forward (in ring order) to the tail. None = a still-leased slot
        is in the way (the caller falls back to the socket)."""
        if not self._live:
            if self._head + total > self.size:
                self._head = 0
            start = self._head
            self._head = start + total
            return start
        tail = self._live[0][0]
        h = self._head
        if h > tail:
            # free: [h, size) then, wrapping, [0, tail)
            if h + total <= self.size:
                self._head = h + total
                return h
            if total <= tail:
                self._head = total
                return 0
            return None
        if h < tail and total <= tail - h:
            self._head = h + total
            return h
        return None  # head has caught the leased tail: ring is full

    def stats(self) -> dict:
        with self._lock:
            return {"published": self.published, "reused": self.reused,
                    "fallbacks": self.fallbacks,
                    "live_slots": len(self._live),
                    "size": self.size}

    def close(self) -> None:
        """Clean shutdown: unlink the ring file so repeated ephemeral
        clusters (tests, benches, CI) don't accumulate resident tmpfs
        rings. A CRASHED owner never gets here, which is exactly when
        the inode must survive for the respawn to reuse; readers
        holding a mapping of an unlinked ring remap on their next
        validation failure (ShmReader.get)."""
        with self._lock:
            try:
                self._mm.close()
            except (BufferError, ValueError):
                pass  # a live exported view pins the map; tmpfs reclaims
            try:
                os.unlink(self.path)
            except OSError:
                pass


class ShmReader:
    """Forwarder-side mapper. Mmaps are cached per path and NEVER
    closed while the process lives — a memoryview handed into an HTTP
    response must outlive any eviction policy, and the ring paths are
    bounded by the worker count."""

    def __init__(self):
        # path -> (mmap, st_ino): the inode lets a validation failure
        # detect that the owner recreated the ring (clean stop +
        # respawn unlinks and recreates) and remap; the superseded
        # mmap object is simply dropped — live exported views pin it
        # until they die, then Python closes it
        self._maps: dict[str, tuple[mmap.mmap, int]] = {}
        self._lock = threading.Lock()

    def _open_map(self, path: str) -> Optional[tuple[mmap.mmap, int]]:
        try:
            fd = os.open(path, os.O_RDONLY)
            try:
                st = os.fstat(fd)
                mm = mmap.mmap(fd, st.st_size, prot=mmap.PROT_READ)
            finally:
                os.close(fd)
        except (OSError, ValueError) as e:
            log.debug("shm map of %s failed: %s", path, e)
            return None
        return mm, st.st_ino

    def _map(self, path: str, remap: bool = False):
        with self._lock:
            ent = self._maps.get(path)
            if ent is not None and not remap:
                return ent[0]
            if ent is not None and remap:
                try:
                    if os.stat(path).st_ino == ent[1]:
                        return ent[0]  # same inode: nothing to remap
                except OSError:
                    return ent[0]
            new = self._open_map(path)
            if new is None:
                return ent[0] if ent is not None else None
            self._maps[path] = new
            return new[0]

    def get(self, ref: dict, hash32: bytes) -> Optional[memoryview]:
        """Resolve a publish() reference -> memoryview over the mapped
        payload, or None when anything about the slot disagrees with
        the reference (wrapped ring, stale epoch, truncated file) —
        the caller re-fetches over the socket."""
        try:
            path, off = ref["path"], int(ref["off"])
            seq, n = int(ref["seq"]), int(ref["len"])
        except (KeyError, TypeError, ValueError):
            return None
        mm = self._map(path)
        for attempt in range(2):
            if mm is None or off < 0 or off + HEADER_SIZE + n > len(mm):
                return None
            magic, got_seq, got_len, got_hash = HEADER.unpack(
                bytes(mm[off:off + HEADER.size]))
            if magic == MAGIC and got_seq == seq and got_len == n \
                    and got_hash == bytes(hash32):
                return memoryview(mm)[off + HEADER_SIZE:
                                      off + HEADER_SIZE + n]
            if attempt == 0:
                # the owner may have recreated the ring since we
                # mapped it (clean-stop respawn): remap once if the
                # inode changed, else the reference is simply stale
                mm = self._map(path, remap=True)
        return None
