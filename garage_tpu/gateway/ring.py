"""Rendezvous-hash ownership of cacheable block hashes across gateway
workers.

Highest-random-weight beats a modulo ring here because membership
changes are common (worker crash/respawn) and must remap ONLY the dead
worker's share: every surviving worker keeps exactly the keys it
already owns, so a respawn invalidates nothing that is still hot.
Ownership is computed from the blake2b of (member id ‖ block hash) —
deterministic across processes, no coordination beyond agreeing on the
member list (the lease roster, which every worker refreshes each renew).
"""

from __future__ import annotations

import hashlib
from typing import Optional


def _weight(member: bytes, hash32: bytes) -> bytes:
    return hashlib.blake2b(member + hash32, digest_size=8).digest()


def rendezvous_owner(members, hash32: bytes) -> Optional[bytes]:
    """Highest-random-weight owner of `hash32` among `members` (any
    iterable of node ids), or None when empty. Shared by the worker
    ring below and the CLUSTER cache tier (block/cache_tier.py), so
    both layers agree on what 'owner' means and a future weighting
    change cannot drift between them."""
    best = None
    best_w = b""
    for m in members:
        w = _weight(m, hash32)
        if best is None or w > best_w:
            best, best_w = m, w
    return best


class CacheRing:
    def __init__(self, self_id: bytes):
        self.self_id = self_id
        self._members: list[bytes] = []

    def set_members(self, members: list[bytes]) -> None:
        # order-insensitive: every worker must compute the same owner
        # from the same roster regardless of arrival order
        self._members = sorted(set(members))

    @property
    def members(self) -> list[bytes]:
        return list(self._members)

    def owner(self, hash32: bytes) -> Optional[bytes]:
        """The owning member id, or None when routing is moot (fewer
        than two members, or we are not in the roster yet)."""
        if len(self._members) < 2 or self.self_id not in self._members:
            return None
        return max(self._members, key=lambda m: _weight(m, hash32))

    def owner_of(self, hash32: bytes) -> Optional[bytes]:
        """Remote owner to forward to, or None when this worker should
        serve (it owns the hash, or routing is moot)."""
        owner = self.owner(hash32)
        if owner is None or owner == self.self_id:
            return None
        return owner

    def owns(self, hash32: bytes) -> bool:
        """Whether this worker should hold the cached copy. True when
        routing is moot: an unsharded cache owns everything it sees."""
        owner = self.owner(hash32)
        return owner is None or owner == self.self_id
