"""Gateway supervisor: runs inside the store node process.

Forks N worker processes (`python -m garage_tpu.gateway.worker`), each
an API-only Garage node binding the frontend ports with SO_REUSEPORT,
and then:

  * brokers qos budget leases over the `garage_tpu/gateway` RPC
    endpoint (workers renew every `[gateway] lease_interval_s`; the
    broker rebalances by observed demand — lease.py);
  * respawns crashed workers, rate-limited by `respawn_backoff_s`, and
    drains a dead worker's lease straight back into the pool;
  * hands every renew the live worker roster, which is what the
    worker-sharded read cache hashes block ownership over (ring.py);
  * fans runtime-knob writes (tuning/qos/chaos) out to all workers and
    pulls their /metrics renders for the aggregated exposition.

Worker identity is stable across respawns: worker i keeps its node key
under `{metadata_dir}/gateway/worker{i}`, so a respawned process
reconnects as the same peer and the roster (hence cache ownership)
does not churn.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

from ..net.message import PRIO_NORMAL
from ..utils.background import spawn
from ..utils.error import RpcError
from . import GATEWAY_RPC_PATH
from .lease import BudgetLeaseBroker

log = logging.getLogger("garage_tpu.gateway.supervisor")


def resolve_workers(configured: int) -> int:
    """0 = auto(cpu_count); 1 = single-process (no supervisor)."""
    if configured == 0:
        return os.cpu_count() or 1
    return max(1, int(configured))


@dataclass
class WorkerProc:
    index: int
    proc: Optional[subprocess.Popen] = None
    node_id: Optional[bytes] = None
    restarts: int = 0
    last_spawn: float = field(default_factory=time.monotonic)
    ready: bool = False  # first hello received since (re)spawn

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None


class GatewaySupervisor:
    def __init__(self, garage, config_path: str,
                 n_workers: Optional[int] = None):
        self.garage = garage
        self.config_path = config_path
        cfg = garage.config
        self.gw_cfg = cfg.gateway
        self.n = n_workers if n_workers is not None \
            else resolve_workers(self.gw_cfg.workers)
        self.broker = BudgetLeaseBroker(
            cfg.qos.global_rps, cfg.qos.global_bytes_per_s,
            min_share=self.gw_cfg.min_share,
            ttl_s=self.gw_cfg.lease_ttl_s,
            expected_workers=self.n)
        self.endpoint = garage.system.netapp.endpoint(
            GATEWAY_RPC_PATH).set_handler(self._handle)
        self.workers: dict[int, WorkerProc] = {}
        self.restarts_total = 0
        self._stopping = False
        self._monitor_task: Optional[asyncio.Task] = None
        # runtime knobs fanned out since boot, replayed to a respawned
        # worker on its hello — a fresh process starts from the on-disk
        # config and would otherwise silently diverge from its siblings
        # (tuning/qos merge by key; chaos is an ordered log, compacted
        # at each clear=True spec)
        self._knob_state: dict[str, dict] = {"tuning": {}, "qos": {}}
        self._chaos_log: list[dict] = []
        garage.gateway_supervisor = self

    # ---- lifecycle -----------------------------------------------------

    def _store_peer(self) -> str:
        host, _, port = self.garage.config.rpc_bind_addr.rpartition(":")
        host = host.strip("[]")
        if host in ("0.0.0.0", "::", ""):
            host = "127.0.0.1"  # workers are always on this host
        return f"{self.garage.system.id.hex()}@{host}:{port}"

    def _spawn(self, index: int) -> None:
        wp = self.workers.setdefault(index, WorkerProc(index))
        argv = [sys.executable, "-m", "garage_tpu.gateway.worker",
                "--config", self.config_path,
                "--index", str(index), "--workers", str(self.n),
                "--store", self._store_peer()]
        # inherit stdout/stderr: worker logs land next to the store's.
        # Workers never print the harness "ready" line — the supervisor
        # announces readiness only once every worker has said hello.
        wp.proc = subprocess.Popen(argv)
        wp.last_spawn = time.monotonic()
        wp.ready = False
        log.info("gateway worker %d spawned (pid %d)", index, wp.proc.pid)

    async def start(self, ready_timeout: float = 120.0) -> None:
        for bind in (self.garage.config.s3_api_bind_addr,
                     self.garage.config.k2v_api_bind_addr,
                     self.garage.config.web_bind_addr):
            if bind and bind.startswith("/"):
                raise RuntimeError(
                    "[gateway] workers > 1 requires TCP frontend binds "
                    "(SO_REUSEPORT does not apply to unix sockets): "
                    f"{bind}")
        for i in range(self.n):
            await asyncio.to_thread(self._spawn, i)
        self._monitor_task = spawn(self._monitor_loop(),
                                   "gateway-supervisor-monitor")
        deadline = time.monotonic() + ready_timeout
        while time.monotonic() < deadline:
            if all(wp.ready for wp in self.workers.values()):
                log.info("gateway up: %d workers ready", self.n)
                return
            await asyncio.sleep(0.1)
        missing = [i for i, wp in self.workers.items() if not wp.ready]
        # failed startup must not orphan forked workers: they hold the
        # SO_REUSEPORT frontend port and their per-index lockfiles, and
        # would wedge every subsequent start of this node
        await self.stop()
        raise RuntimeError(f"gateway workers {missing} not ready after "
                           f"{ready_timeout:.0f}s")

    async def _monitor_loop(self) -> None:
        backoff = max(0.1, self.gw_cfg.respawn_backoff_s)
        while not self._stopping:
            await asyncio.sleep(0.25)
            self.broker.expire()
            for wp in self.workers.values():
                if self._stopping or wp.alive:
                    continue
                if wp.ready:
                    # just noticed the death: drain the lease back to
                    # the pool immediately — the budget must not sit
                    # idle in a corpse while the survivors shed
                    wp.ready = False
                    self.broker.revoke(f"w{wp.index}")
                    log.warning(
                        "gateway worker %d died (pid %s), lease drained",
                        wp.index, wp.pid)
                if time.monotonic() - wp.last_spawn >= backoff:
                    wp.restarts += 1
                    self.restarts_total += 1
                    # fork+exec off the loop: a slow spawn (cold page
                    # cache, cgroup pressure) must not stall the
                    # supervisor's own frontends
                    await asyncio.to_thread(self._spawn, wp.index)

    async def stop(self) -> None:
        self._stopping = True
        if self._monitor_task is not None:
            self._monitor_task.cancel()
        for wp in self.workers.values():
            if wp.alive:
                wp.proc.send_signal(signal.SIGTERM)
        for wp in self.workers.values():
            if wp.proc is not None:
                try:
                    await asyncio.to_thread(wp.proc.wait, 10)
                except subprocess.TimeoutExpired:
                    wp.proc.kill()

    # ---- worker RPC (lease protocol) -----------------------------------

    async def _handle(self, from_node, payload, stream):
        op = payload.get("op")
        if op in ("hello", "renew"):
            idx = int(payload["index"])
            wp = self.workers.get(idx)
            if wp is not None:
                newly_ready = not wp.ready
                wp.node_id = from_node
                wp.ready = True
                if newly_ready and (self._chaos_log
                                    or any(self._knob_state.values())):
                    # respawned (or late) worker: bring it up to the
                    # knob state its siblings already carry, off the
                    # hello path so the lease reply is not delayed
                    spawn(self._replay_knobs(wp),
                          f"gateway-knob-replay-{idx}")
            lease = self.broker.renew(
                f"w{idx}",
                float(payload.get("demand_rps", 0.0)),
                float(payload.get("demand_bps", 0.0)))
            return {
                "lease": lease.to_dict(),
                "roster": self.roster(),
                "interval_s": self.gw_cfg.lease_interval_s,
                "cache_shard": bool(self.gw_cfg.cache_shard),
            }
        raise RpcError(f"unknown gateway op {op!r}")

    def roster(self) -> list[list]:
        """Alive workers with known node ids, [(index, node_id hex,
        rpc addr|None)] — the membership the worker-sharded cache
        hashes over. Addresses (learned from each worker's peering
        hello) let siblings dial each other immediately instead of
        waiting out the ping-driven peer exchange."""
        peers = self.garage.system.peering.peers
        out = []
        for wp in sorted(self.workers.values(), key=lambda w: w.index):
            if not (wp.alive and wp.node_id is not None and wp.ready):
                continue
            p = peers.get(wp.node_id)
            addr = list(p.addr) if p is not None and p.addr else None
            out.append([wp.index, wp.node_id.hex(), addr])
        return out

    # ---- fan-out -------------------------------------------------------

    async def _replay_knobs(self, wp: WorkerProc) -> None:
        ops: list[tuple[str, dict]] = []
        for knob in ("tuning", "qos"):
            if self._knob_state[knob]:
                ops.append((knob, dict(self._knob_state[knob])))
        ops.extend(("chaos", s) for s in list(self._chaos_log))
        for op, spec in ops:
            try:
                await self.endpoint.call(
                    wp.node_id, {"op": op, "spec": spec}, PRIO_NORMAL,
                    timeout=10.0)
            except Exception as e:
                log.warning("knob replay (%s) to worker %d failed: %s",
                            op, wp.index, e)

    def _record_knobs(self, payload: dict) -> None:
        op, spec = payload.get("op"), payload.get("spec")
        if not isinstance(spec, dict) or not spec:
            return
        if op in ("tuning", "qos"):
            self._knob_state[op].update(spec)
        elif op == "chaos":
            if spec.get("clear"):
                self._chaos_log.clear()
            self._chaos_log.append(dict(spec))

    async def fanout(self, payload: dict, timeout: float = 10.0) -> dict:
        """Send one op to every ready worker; per-worker result or
        {"error": ...} — a worker mid-respawn must not fail the whole
        operator call. Knob-writing ops are recorded for replay to
        future respawns."""
        self._record_knobs(payload)
        async def one(wp: WorkerProc):
            try:
                resp, _ = await self.endpoint.call(
                    wp.node_id, payload, PRIO_NORMAL, timeout=timeout)
                return wp.index, resp
            except Exception as e:
                return wp.index, {"error": str(e)}

        targets = [wp for wp in self.workers.values()
                   if wp.alive and wp.node_id is not None and wp.ready]
        results = await asyncio.gather(*(one(wp) for wp in targets))
        return {idx: resp for idx, resp in results}

    # ---- surface -------------------------------------------------------

    def state(self) -> dict:
        # list() snapshot: state() runs on the /metrics scrape thread
        # while _spawn (loop) can insert into self.workers
        workers = sorted(list(self.workers.values()),
                         key=lambda w: w.index)
        return {
            "enabled": True,
            "workers_configured": self.n,
            "workers_alive": sum(1 for wp in workers if wp.alive),
            "restarts_total": self.restarts_total,
            "workers": [{
                "index": wp.index, "pid": wp.pid, "alive": wp.alive,
                "ready": wp.ready, "restarts": wp.restarts,
                "node": (wp.node_id.hex()[:16] if wp.node_id else None),
                "lease": dict(zip(("rps", "bytes_per_s"),
                                  self.broker.granted(f"w{wp.index}"))),
            } for wp in workers],
            "broker": self.broker.state(),
        }
