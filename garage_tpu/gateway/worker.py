"""Gateway worker process: `python -m garage_tpu.gateway.worker`.

Each worker is an API-only Garage node: no capacity (it never appears
in the layout, so no table partition or block is ever placed on it), a
`memory` metadata engine (workers hold no durable state — everything
authoritative lives in the store node, reached over the existing
loopback `net/` RPC transport), and its own node key under
`{metadata_dir}/gateway/worker{i}` so a respawn reconnects as the same
peer. The S3/K2V/web frontends bind the SAME ports as every sibling
via SO_REUSEPORT — the kernel balances accepted connections across
workers, giving the node one accept loop, one SigV4/chunk-hash thread
pool and one GIL **per core** instead of per node.

The worker's qos global buckets are not configured limits but LEASES:
`GatewayWorkerClient` renews a share of the node budget from the
supervisor's broker every `lease_interval_s`, reporting observed
demand (offered req/s and bytes/s EWMA'd broker-side) so hot workers
grow and idle ones shrink to the floor. If the supervisor goes silent
past the lease TTL the worker clamps itself to `min_share` of its last
grant — a partitioned worker must fail toward admitting less than its
share, never more.

The same client implements the worker-sharded read cache: each renew
carries the live roster, cacheable block hashes are owned by
rendezvous hash over it (ring.py), and a non-owner forwards the read
to the owner over worker-to-worker RPC instead of decoding its own
duplicate copy. SSE-C payloads never route (cacheable=False skips the
router entirely) and the forwarding worker charges its own lease for
the bytes (the owner serves uncharged).
"""

from __future__ import annotations

import argparse
import asyncio
import copy
import logging
import os
import signal
import time
from typing import Optional

from ..net.message import PRIO_NORMAL
from ..utils.background import spawn
from ..utils.config import Config, read_config
from . import GATEWAY_RPC_PATH
from .ring import CacheRing

log = logging.getLogger("garage_tpu.gateway.worker")


def derive_worker_config(cfg: Config, index: int, workers: int,
                         store_peer: str) -> Config:
    """The worker's view of the node config: API knobs inherited
    verbatim, state and background work stripped, per-process RAM
    budgets divided by the worker count so the NODE totals what the
    operator configured."""
    w = copy.deepcopy(cfg)
    w.metadata_dir = os.path.join(cfg.metadata_dir, "gateway",
                                  f"worker{index}")
    w.data_dir = []
    w.db_engine = "memory"
    w.rpc_bind_addr = "127.0.0.1:0"  # ephemeral; netapp fixes up
    w.rpc_public_addr = None
    w.bootstrap_peers = [store_peer]
    w.admin_api_bind_addr = None  # admin stays on the supervisor
    w.metadata_auto_snapshot_interval = None
    w.qos = copy.deepcopy(cfg.qos)
    w.qos.governor = False  # nothing background to govern here
    # leased budgets arrive with the first hello, BEFORE the frontends
    # bind; starting from None (unlimited) is safe because no port is
    # accepting yet
    w.qos.global_rps = None
    w.qos.global_bytes_per_s = None
    # the concurrency gate is a node-wide memory/latency bound like the
    # rate budgets: split it statically so N workers cannot hold N× the
    # configured in-flight requests (per-key/per-bucket rates stay
    # per-worker approximations — documented in README)
    if cfg.qos.max_concurrent is not None:
        w.qos.max_concurrent = max(1, cfg.qos.max_concurrent
                                   // max(1, workers))
    w.qos.max_queue = max(1, cfg.qos.max_queue // max(1, workers))
    # no external discovery per worker — the store node already
    # advertises the cluster
    w.consul_http_addr = None
    w.kubernetes_namespace = None
    n = max(1, workers)
    w.block_ram_buffer_max = max(1 << 20, cfg.block_ram_buffer_max // n)
    base_cache = (cfg.block_read_cache_max_bytes
                  if cfg.block_read_cache_max_bytes is not None
                  else cfg.block_ram_buffer_max // 4)
    w.block_read_cache_max_bytes = base_cache // n
    return w


class GatewayWorkerClient:
    """Lease client + cache router + runtime-knob receiver, all over
    the one `garage_tpu/gateway` endpoint."""

    def __init__(self, garage, index: int, store_id: bytes,
                 gw_cfg, admin_http=None):
        self.garage = garage
        self.index = index
        self.store_id = store_id
        self.gw_cfg = gw_cfg
        self.endpoint = garage.system.netapp.endpoint(
            GATEWAY_RPC_PATH).set_handler(self._handle)
        self.ring = CacheRing(garage.system.id)
        # zero-copy shm forwards (gateway/shm.py): the owner side
        # publishes payloads into its ring, the forwarding side maps
        # every sibling's ring read-only. `[gateway] shm_forwards =
        # false` is the kill switch — both stay None and every forward
        # carries bytes over the socket.
        self.shm = None
        self.shm_reader = None
        if getattr(gw_cfg, "shm_forwards", False):
            from .shm import ShmReader, ShmRing, ring_path

            try:
                # the worker's own metadata_dir is stable per index and
                # unique per cluster — exactly the ring-path key a
                # respawn must reuse and parallel clusters must not
                # share. Forwarders never derive paths: the reference
                # in the RPC reply carries the owner's path verbatim.
                self.shm = ShmRing(
                    ring_path(garage.config.metadata_dir, index),
                    gw_cfg.shm_ring_bytes, gw_cfg.shm_lease_s)
                self.shm_reader = ShmReader()
            except OSError as e:
                log.warning("shm forwards disabled (ring create "
                            "failed): %s", e)
        self.interval = gw_cfg.lease_interval_s
        self.lease: Optional[dict] = None
        self._last_ok = time.monotonic()
        self._clamped = False
        self._prev_sample = (time.monotonic(), 0.0, 0)
        self._renew_task: Optional[asyncio.Task] = None
        self._stopped = False
        # render /metrics for the supervisor's aggregation without
        # binding an HTTP port of our own
        if admin_http is None:
            from ..admin.http import AdminHttpServer

            admin_http = AdminHttpServer(garage)
        self._admin = admin_http

    # ---- lease protocol ------------------------------------------------

    def _demand_sample(self) -> tuple[float, float]:
        """Observed offered load since the last renew: requests/s
        (admitted + shed — a shedding worker is exactly the one whose
        lease must grow) and bytes/s."""
        c = self.garage.qos.counters
        now = time.monotonic()
        t0, req0, by0 = self._prev_sample
        req1 = float(c.admitted + c.shed)
        by1 = c.offered_bytes
        self._prev_sample = (now, req1, by1)
        dt = max(now - t0, 1e-3)
        return (req1 - req0) / dt, (by1 - by0) / dt

    async def _renew_once(self, op: str = "renew") -> None:
        d_rps, d_bps = self._demand_sample()
        resp, _ = await self.endpoint.call(
            self.store_id,
            {"op": op, "index": self.index,
             "demand_rps": d_rps, "demand_bps": d_bps},
            PRIO_NORMAL, timeout=max(2.0, self.interval * 2))
        self._apply(resp)
        self._last_ok = time.monotonic()
        self._clamped = False

    def _apply(self, resp: dict) -> None:
        lease = resp.get("lease") or {}
        self.lease = lease
        self.interval = float(resp.get("interval_s", self.interval))
        rps = lease.get("rps")
        bps = lease.get("bytes_per_s")
        self.garage.qos.update_limits({
            "global_rps": rps, "global_burst": rps,
            "global_bytes_per_s": bps, "global_bytes_burst": bps,
        })
        members = []
        for entry in resp.get("roster", []):
            _, hexid, addr = (entry + [None])[:3]
            nid = bytes.fromhex(hexid)
            members.append(nid)
            if nid != self.garage.system.id and addr:
                # seed the sibling's address so the peering connect
                # loop dials it NOW — cache forwards must not wait for
                # the ping-driven peer exchange to converge
                self.garage.system.peering.add_peer(tuple(addr), nid)
        if resp.get("cache_shard") and len(members) > 1:
            self.ring.set_members(members)
            self.garage.block_manager.cache_router = self
        else:
            self.garage.block_manager.cache_router = None

    def _clamp_to_floor(self) -> None:
        """Supervisor silent past the lease TTL: shrink to min_share of
        the last grant. Fail toward admitting LESS than our share."""
        if self._clamped or not self.lease:
            return
        self._clamped = True
        frac = self.gw_cfg.min_share
        rps = self.lease.get("rps")
        bps = self.lease.get("bytes_per_s")
        self.garage.qos.update_limits({
            "global_rps": rps * frac if rps is not None else None,
            "global_bytes_per_s": bps * frac if bps is not None
            else None,
        })
        log.warning("worker %d lease expired without renewal; "
                    "clamped to %.0f%% of last grant", self.index,
                    frac * 100)

    async def start(self, deadline_s: float = 60.0) -> None:
        """Connect to the store and obtain the first lease; the caller
        binds the frontends only after this returns."""
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                await self._renew_once(op="hello")
                break
            except Exception as e:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"worker {self.index}: no lease from store "
                        f"after {deadline_s:.0f}s: {e}") from e
                await asyncio.sleep(0.2)
        self._renew_task = spawn(self._renew_loop(),
                                 f"gateway-lease-renew-{self.index}")

    async def _renew_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.interval)
            try:
                # lint: ignore[GL12] single-task loop — only this coroutine calls _renew_once, so its lease/_last_ok writes never interleave with the except-path reads
                await self._renew_once()
            except Exception as e:
                log.debug("lease renew failed: %s", e)
                ttl = (self.lease or {}).get("ttl_s",
                                             self.gw_cfg.lease_ttl_s)
                if time.monotonic() - self._last_ok > ttl:
                    self._clamp_to_floor()

    def stop(self) -> None:
        self._stopped = True
        if self._renew_task is not None:
            self._renew_task.cancel()
        if self.shm is not None:
            self.shm.close()

    # ---- cache router (BlockManager.cache_router duck-type) ------------

    def owner_of(self, hash32: bytes) -> Optional[bytes]:
        return self.ring.owner_of(hash32)

    def owns(self, hash32: bytes) -> bool:
        return self.ring.owns(hash32)

    async def forward(self, owner: bytes, hash32: bytes):
        """Read a cacheable block through its owner worker; None means
        'serve it yourself' (owner unreachable). The owner answers with
        a shm reference when it can (gateway/shm.py) — the payload then
        never crosses the socket: we map the owner's ring and hand the
        memoryview straight down the zero-copy HTTP write path. A
        reference that fails validation (wrapped ring, stale epoch)
        falls back to one explicit socket re-fetch."""
        from ..utils.metrics import registry

        try:
            resp, _ = await self.endpoint.call(
                owner, {"op": "cache_get", "hash": hash32},
                PRIO_NORMAL, timeout=10.0)
            if not isinstance(resp, dict):
                resp = {}
            ref = resp.get("shm")
            if ref is not None and self.shm_reader is not None:
                mv = self.shm_reader.get(ref, hash32)
                if mv is not None:
                    registry().inc("cache_tier_shm_forward")
                    registry().inc("gateway_cache_forward_ok")
                    return mv
                registry().inc("cache_tier_shm_fallback")
                resp, _ = await self.endpoint.call(
                    owner, {"op": "cache_get", "hash": hash32,
                            "no_shm": True},
                    PRIO_NORMAL, timeout=10.0)
                if not isinstance(resp, dict):
                    resp = {}
            data = resp.get("data")
            if data is not None:
                registry().inc("gateway_cache_forward_ok")
                return data
        except Exception as e:
            log.debug("cache forward to %s failed: %s",
                      owner[:4].hex(), e)
        registry().inc("gateway_cache_forward_fail")
        return None

    # ---- RPC handler ---------------------------------------------------

    async def _handle(self, from_node, payload, stream):
        from ..admin.http import (apply_chaos_spec, apply_s3_tuning,
                                  s3_tuning_state)

        op = payload.get("op")
        if op == "ping":
            return {"ok": True, "index": self.index}
        if op == "cache_get":
            from ..utils.metrics import registry
            from .shm import SHM_MIN_BYTES

            h = payload["hash"]
            data = await self.garage.block_manager.rpc_get_block(
                h, cacheable=True, route=False, charge=False)
            registry().inc("gateway_cache_forward_served")
            # zero-copy reply: publish once into our shm ring and ship
            # the tiny reference instead of the payload. Small payloads
            # and a lease-exhausted ring take the socket as before.
            if self.shm is not None and not payload.get("no_shm") \
                    and len(data) >= SHM_MIN_BYTES:
                ref = self.shm.publish(h, data)
                if ref is not None:
                    registry().inc("cache_tier_shm_publish")
                    return {"shm": ref}
            return {"data": data}
        if op == "metrics":
            text = await asyncio.to_thread(self._admin.render_metrics)
            return {"text": text}
        if op == "tuning":
            return apply_s3_tuning(self.garage, payload.get("spec") or {})
        if op == "tuning_state":
            return s3_tuning_state(self.garage)
        if op == "qos":
            self.garage.qos.update_limits(payload.get("spec") or {})
            return self.garage.qos.state()
        if op == "qos_state":
            return self.garage.qos.state()
        if op == "chaos":
            return apply_chaos_spec(payload.get("spec") or {})
        from ..utils.error import RpcError

        raise RpcError(f"unknown gateway worker op {op!r}")


async def run_worker(cfg_path: str, index: int, workers: int,
                     store: str) -> None:
    from ..utils.runtime import tune

    tune()
    cfg = await asyncio.to_thread(read_config, cfg_path)
    from ..model.garage import parse_peer

    store_addr, store_id = parse_peer(store)
    if store_id is None:
        raise ValueError("--store must be '<hex node id>@host:port'")
    wcfg = derive_worker_config(cfg, index, workers, store)
    os.makedirs(wcfg.metadata_dir, exist_ok=True)
    from ..utils import lockfile

    lock_fd = lockfile.acquire(wcfg.metadata_dir, "server")
    try:
        await _run_worker_locked(cfg, wcfg, index, store_id)
    finally:
        # released on EVERY exit (GL11): a worker that dies during
        # boot must not wedge its per-index lockfile for the respawn
        # (the PR 8 orphan-worker failure shape)
        lockfile.release(lock_fd)


async def _run_worker_locked(cfg, wcfg, index: int,
                             store_id: bytes) -> None:
    from ..model.garage import Garage

    garage = Garage(wcfg)
    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for name in ("SIGINT", "SIGTERM", "SIGHUP"):
        sig = getattr(signal, name, None)
        if sig is None:
            continue
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass

    # API-only: gossip + RPC listen, but no table/block/scrub workers —
    # the store node keeps all background work
    system_task = asyncio.create_task(garage.run(spawn_workers=False))
    client = GatewayWorkerClient(garage, index, store_id, cfg.gateway)
    await client.start()

    from ..api.s3.api_server import S3ApiServer
    from ..model.garage import parse_addr

    servers = []
    s3 = None
    if cfg.s3_api_bind_addr:
        s3 = S3ApiServer(garage)
        await s3.start(*parse_addr(cfg.s3_api_bind_addr),
                       reuse_port=True)
        servers.append(s3)
    if cfg.k2v_api_bind_addr:
        from ..api.k2v.api_server import K2VApiServer

        k2v = K2VApiServer(garage)
        await k2v.start(*parse_addr(cfg.k2v_api_bind_addr),
                        reuse_port=True)
        servers.append(k2v)
    if cfg.web_bind_addr:
        from ..web.server import WebServer

        web = WebServer(garage, s3)
        await web.start(*parse_addr(cfg.web_bind_addr), reuse_port=True)
        servers.append(web)

    log.info("gateway worker %d up (node %s, store %s)", index,
             garage.system.id.hex()[:16], store_id.hex()[:16])
    await stop.wait()
    log.info("gateway worker %d shutting down", index)
    client.stop()
    for s in servers:
        await s.stop()
    await garage.stop()
    system_task.cancel()


def main() -> None:
    p = argparse.ArgumentParser(prog="garage_tpu.gateway.worker")
    p.add_argument("--config", "-c", required=True)
    p.add_argument("--index", type=int, required=True)
    p.add_argument("--workers", type=int, required=True)
    p.add_argument("--store", required=True,
                   help="store node as '<hex id>@host:port'")
    p.add_argument("--log-level",
                   default=os.environ.get("RUST_LOG", "info"))
    args = p.parse_args()
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format=f"%(asctime)s %(levelname)s [w{args.index}] "
               "%(name)s: %(message)s",
    )
    asyncio.run(run_worker(args.config, args.index, args.workers,
                           args.store))


if __name__ == "__main__":
    main()
