"""Token buckets, a bounded concurrency gate, and the QosEngine that
composes them into the node's admission-control plane.

Design rules:

  * A limiter NEVER queues unboundedly. Each acquire states the most it
    is willing to wait; if granting would exceed that, the request is
    shed immediately with a `SlowDown` carrying the earliest time a
    retry could succeed (`Retry-After`).
  * Buckets admit debt: a granted-but-waiting acquire subtracts its
    tokens up front (tokens go negative), which makes grants FIFO-fair
    under concurrency without a waiter queue — later acquires see the
    debt and compute a longer wait.
  * Unset limits cost nothing: every check short-circuits on None, so a
    node with no [qos] config behaves exactly as before.

Clock injection (`clock=`) keeps the refill math unit-testable without
sleeping.
"""

from __future__ import annotations

import asyncio
import contextvars
import math
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils import sanitizer

# identity of the request currently executing on this task, set by the
# API frontends once auth resolves (api/s3/api_server.py). Charges deep
# in the stack (block reads, chunk shaping) read it so per-key fairness
# works without threading a key argument through every seam; tasks
# spawned by a request (readahead prefetch) inherit it by contextvar
# copy semantics.
CURRENT_QOS_KEY: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("garage_qos_key", default=None)


class SlowDown(Exception):
    """Admission denied: the caller should retry after `retry_after`
    seconds. API frontends translate this into `503 SlowDown` (S3) /
    a JSON 503 (K2V, admin) with a `Retry-After` header."""

    def __init__(self, retry_after: float, scope: str = "global"):
        self.retry_after = max(retry_after, 0.0)
        self.scope = scope
        super().__init__(
            f"admission denied ({scope}); retry after "
            f"{self.retry_after:.2f}s")

    def header_value(self) -> str:
        # Retry-After is integer seconds; never advertise 0 (clients
        # would busy-spin the shed path)
        return str(max(1, math.ceil(self.retry_after)))


class TokenBucket:
    """Token bucket over an arbitrary unit (requests, bytes).

    `rate` tokens refill per second up to `burst`. acquire(n) grants
    immediately when tokens cover n; otherwise the caller owes a wait of
    deficit/rate seconds — granted (as debt) when within `max_wait`,
    shed otherwise. Single-event-loop discipline: no lock is needed
    because there is no await between the read and the debit.
    """

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.configure(rate, burst)
        sanitizer.track_conservation(self)  # no-op unless armed

    @property
    def conservation_ok(self) -> bool:
        """Clamp invariant: refund/refill can never bank more than one
        burst — a violation means tokens were minted, not returned
        (checked at loop teardown under GARAGE_SANITIZE=1)."""
        return self.tokens <= self.burst * (1 + 1e-9)

    def configure(self, rate: float, burst: Optional[float] = None) -> None:
        """Runtime retune; preserves the current fill fraction so a
        limit change mid-traffic neither forgives debt nor confiscates
        saved burst."""
        old_frac = None
        if getattr(self, "rate", None):
            old_frac = self.tokens / self.burst if self.burst else 1.0
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else float(rate)
        self.tokens = (self.burst if old_frac is None
                       else old_frac * self.burst)
        self._t_last = self.clock()

    def _refill(self) -> None:
        now = self.clock()
        dt = now - self._t_last
        if dt > 0:
            self.tokens = min(self.burst, self.tokens + dt * self.rate)
            self._t_last = now

    def wait_for(self, n: float) -> float:
        """Seconds until n tokens could be granted (0 = grantable now).
        Pure query — does not debit."""
        self._refill()
        if self.tokens >= n:
            return 0.0
        return (n - self.tokens) / self.rate if self.rate > 0 else math.inf

    def try_acquire(self, n: float = 1.0) -> bool:
        """Grant n tokens iff available right now (no debt)."""
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def refund(self, n: float) -> None:
        """Return tokens a multi-stage admission debited before a later
        stage shed the request — the budget must not be consumed by
        work that never happened."""
        self.tokens = min(self.burst, self.tokens + n)

    async def acquire(self, n: float = 1.0, max_wait: float = 0.0,
                      scope: str = "global") -> float:
        """Grant n tokens, sleeping up to max_wait for refill; raises
        SlowDown when the bounded wait would be exceeded. Returns the
        seconds actually waited (0.0 on the fast path)."""
        wait = self.wait_for(n)
        if wait <= 0:
            self.tokens -= n
            return 0.0
        if wait > max_wait:
            raise SlowDown(wait, scope)
        self.tokens -= n  # debt: reserves our slot FIFO-fairly
        try:
            await asyncio.sleep(wait)
        except BaseException:
            # cancelled mid-wait (client gave up): the work never
            # happened, so the reservation must not leak
            self.tokens += n
            raise
        return wait


class ConcurrencyLimiter:
    """Bounded in-flight gate with a bounded wait queue.

    At most `limit` holders; at most `max_queue` waiters beyond that —
    the next arrival is shed with a Retry-After estimated from the
    recent mean hold time (so clients back off roughly one service
    time, not a constant guess).
    """

    def __init__(self, limit: int, max_queue: int = 0):
        self.active = 0
        self._waiters: list[asyncio.Future] = []
        self._hold_ewma = 0.05  # seconds; seeded at a plausible value
        self.configure(limit, max_queue)

    def configure(self, limit: int, max_queue: int = 0) -> None:
        self.limit = int(limit)
        self.max_queue = int(max_queue)
        # a raised limit must take effect NOW, not after the waiter
        # queue happens to drain: hand the new headroom to the queue
        self._wake_waiters()

    def _wake_waiters(self) -> None:
        while self._waiters and self.active < self.limit:
            fut = self._waiters.pop(0)
            if not fut.cancelled():
                self.active += 1  # transfer the slot with the wakeup
                fut.set_result(None)

    @property
    def queued(self) -> int:
        return len(self._waiters)

    async def acquire(self, scope: str = "global") -> None:
        if not self._waiters and self.active < self.limit:
            self.active += 1
            return
        if len(self._waiters) >= self.max_queue:
            # every queued waiter ahead of us needs ~one service time
            raise SlowDown(self._hold_ewma * (len(self._waiters) + 1),
                           scope)
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            # the slot is transferred INSIDE release() (active stays
            # accounted), so a fast-path arrival in the handoff window
            # can never oversubscribe the limit
            await fut
        except BaseException:
            if fut in self._waiters:
                self._waiters.remove(fut)
            elif fut.done() and not fut.cancelled():
                self.release(0.0)  # slot was handed to us; give it back
            raise

    def release(self, held_seconds: float) -> None:
        if held_seconds > 0:
            self._hold_ewma += 0.2 * (held_seconds - self._hold_ewma)
        self.active -= 1
        self._wake_waiters()


@dataclass
class QosLimits:
    """Runtime-tunable limit set. None disables that limiter."""

    global_rps: Optional[float] = None
    global_burst: Optional[float] = None  # default: 1s of rate
    global_bytes_per_s: Optional[float] = None
    global_bytes_burst: Optional[float] = None
    per_key_rps: Optional[float] = None
    per_bucket_rps: Optional[float] = None
    max_concurrent: Optional[int] = None
    max_queue: int = 64
    # the bounded wait an admission may spend queued before shedding
    max_wait_s: float = 0.5
    # deficit round-robin across per-key queues when the bytes bucket
    # is contended (see DeficitRoundRobin below)
    fair_keys: bool = True

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__dataclass_fields__}


# per-key / per-bucket bucket maps are capped; beyond this the
# least-recently-used scope's bucket is dropped (it re-creates full,
# i.e. one free burst — acceptable, bounded memory is not)
SCOPE_CACHE_MAX = 1024


# bounded per-entity shed maps: beyond this many distinct keys/buckets
# new entities aggregate under "(other)" — an attacker spraying key ids
# must not grow operator-facing state without bound
SHED_ENTITY_MAX = 256


@dataclass
class QosCounters:
    admitted: int = 0
    shed: int = 0
    queued_waits: int = 0
    queued_seconds: float = 0.0
    shaped_bytes: int = 0
    # bytes the node was ASKED to move (declared at admission or shaped
    # mid-stream), admitted or not: this is offered load, the demand
    # signal the gateway lease broker rebalances worker budgets by
    offered_bytes: int = 0
    shed_by_scope: dict = field(default_factory=dict)
    # WHO is being shed, not just how much (ROADMAP "503 retry
    # ergonomics"): per-key and per-bucket shed counts, surfaced top-N
    # through GET /v1/qos. Only scoped sheds are attributable — global
    # sheds happen before identity is resolved, by design.
    shed_by_key: dict = field(default_factory=dict)
    shed_by_bucket: dict = field(default_factory=dict)

    def count_entity(self, table: dict, name: str) -> None:
        if name not in table and len(table) >= SHED_ENTITY_MAX:
            name = "(other)"
        table[name] = table.get(name, 0) + 1

    @staticmethod
    def _top(table: dict, n: int) -> list[list]:
        return [[k, v] for k, v in sorted(table.items(),
                                          key=lambda kv: -kv[1])[:n]]

    def to_dict(self, top_n: int = 10) -> dict:
        return {
            "admitted": self.admitted, "shed": self.shed,
            "queued_waits": self.queued_waits,
            "queued_seconds": round(self.queued_seconds, 6),
            "shaped_bytes": self.shaped_bytes,
            "offered_bytes": self.offered_bytes,
            "shed_by_scope": dict(self.shed_by_scope),
            "top_shed_keys": self._top(self.shed_by_key, top_n),
            "top_shed_buckets": self._top(self.shed_by_bucket, top_n),
        }


class DeficitRoundRobin:
    """Per-key fairness inside one shared TokenBucket (Shreedhar &
    Varghese deficit round-robin, applied to the qos bytes budget).

    Uncontended, this is invisible: a submit with no queued work and a
    bucket that can grant right now takes the fast path (one
    try_acquire, no task, no future). Under contention, draws queue
    per-key and a pump task drains the queues round-robin: each sweep
    credits every active key one `quantum` of deficit and releases that
    key's FIFO head(s) while the deficit and the bucket cover them — so
    K backlogged keys each get ~1/K of the drain rate regardless of how
    much one of them has queued (the bounded-share property pinned by
    tests/test_gateway.py).

    Never sheds: shaping applies to requests that were already
    admitted (the concurrency limiter bounds how many of those exist,
    which bounds the queues). Cancellation-safe: a waiter abandoned
    mid-queue is skipped at grant time and its bytes are never drawn.
    `sleep` is injectable so tests drive the pump on a fake clock.
    """

    def __init__(self, bucket: TokenBucket, quantum: float = 64 * 1024,
                 sleep=asyncio.sleep):
        self.bucket = bucket
        self.quantum = float(quantum)
        self.sleep = sleep
        # key -> FIFO of (nbytes, future); OrderedDict = round-robin
        # order (a drained key re-registers at the tail)
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._deficit: dict[str, float] = {}
        self._pump_task: Optional[asyncio.Task] = None
        self.granted = 0
        self.sweeps = 0

    @property
    def queued(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def fair_wait_units(self, key: str) -> float:
        """Tokens a NEW arrival of `key` must expect to see drawn
        before its own grant under round-robin: everything already
        queued for ITS key plus ~one quantum per competing key (one
        rotation round). Deliberately NOT the whole cross-key backlog
        — that is what the round-robin shields the arrival from, and a
        shed estimate priced on it would let one flooding key 503
        every fresh key the DRR would serve almost immediately."""
        q = self._queues.get(key)
        own = sum(n for n, _ in q) if q else 0.0
        others = len(self._queues) - (1 if q else 0)
        return own + others * self.quantum

    async def submit(self, key: str, n: float) -> None:
        if not self._queues and self.bucket.try_acquire(n):
            return  # fast path: no backlog, tokens on hand
        fut = asyncio.get_running_loop().create_future()
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = deque()
            self._deficit.setdefault(key, 0.0)
        q.append((float(n), fut))
        if self._pump_task is None or self._pump_task.done():
            from ..utils.background import spawn

            self._pump_task = spawn(self._pump(), "qos-drr-pump")
        try:
            await fut
        except BaseException:
            # abandoned waiter: leave the entry in place (cheap), the
            # pump skips cancelled futures without drawing their bytes
            raise

    async def _pump(self) -> None:
        # rotating round-robin: each iteration visits the HEAD key and
        # moves it to the tail, so after a token-exhaustion sleep the
        # next refill goes to the NEXT key in the circle, not back to
        # the same front-runner — this rotation is what makes one
        # refill-sized trickle still split evenly across keys
        while self._queues:
            key = next(iter(self._queues))
            self._queues.move_to_end(key)
            q = self._queues[key]
            # deficit grows one quantum per visit, capped so an idle
            # spell cannot bank unbounded burst (but always enough to
            # eventually cover the key's largest queued draw)
            self._deficit[key] = min(
                self._deficit[key] + self.quantum,
                self.quantum + max((n for n, _ in q), default=0.0))
            blocked: Optional[float] = None
            while q:
                n, fut = q[0]
                if fut.cancelled():
                    q.popleft()
                    continue
                if n > self._deficit[key]:
                    break  # deficit-capped: more credit next visit
                if not self.bucket.try_acquire(n):
                    blocked = n
                    break
                q.popleft()
                self._deficit[key] -= n
                self.granted += 1
                if not fut.done():
                    fut.set_result(None)
            if not q:
                del self._queues[key]
                del self._deficit[key]
            self.sweeps += 1
            if blocked is not None:
                # out of tokens: sleep until the blocked head could be
                # granted; the rotation already put us at the tail, so
                # the refill is offered to the next key first
                await self.sleep(max(self.bucket.wait_for(blocked),
                                     0.001))


class QosEngine:
    """The node's admission-control plane.

    API frontends call `admit()` (global stage: rps + declared bytes +
    concurrency) around each request and `admit_scoped()` (per-key /
    per-bucket rps) once identity is known. The PUT streaming path
    calls `shape_bytes()` per block for bodies whose length was unknown
    at admission. All stages raise SlowDown on shed.
    """

    def __init__(self, limits: Optional[QosLimits] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.counters = QosCounters()
        self._req_bucket: Optional[TokenBucket] = None
        self._bytes_bucket: Optional[TokenBucket] = None
        self._conc: Optional[ConcurrencyLimiter] = None
        self._key_buckets: dict[str, TokenBucket] = {}
        self._bucket_buckets: dict[str, TokenBucket] = {}
        self._fair: Optional[DeficitRoundRobin] = None
        self._fair_req: Optional[DeficitRoundRobin] = None
        self.limits = QosLimits()
        self.set_limits(limits or QosLimits())

    # ---- configuration -------------------------------------------------

    def set_limits(self, limits: QosLimits) -> None:
        self.limits = limits
        if limits.global_rps is not None:
            burst = limits.global_burst or limits.global_rps
            if self._req_bucket is None:
                self._req_bucket = TokenBucket(limits.global_rps, burst,
                                               clock=self.clock)
            else:
                self._req_bucket.configure(limits.global_rps, burst)
        else:
            self._req_bucket = None
        if limits.global_bytes_per_s is not None:
            burst = limits.global_bytes_burst or limits.global_bytes_per_s
            if self._bytes_bucket is None:
                self._bytes_bucket = TokenBucket(
                    limits.global_bytes_per_s, burst, clock=self.clock)
            else:
                self._bytes_bucket.configure(limits.global_bytes_per_s,
                                             burst)
        else:
            self._bytes_bucket = None
        if self._bytes_bucket is not None and limits.fair_keys:
            if self._fair is None or self._fair.bucket \
                    is not self._bytes_bucket:
                self._fair = DeficitRoundRobin(self._bytes_bucket)
        else:
            self._fair = None
        # per-key DRR for the REQUEST-RATE bucket too (ISSUE 15
        # satellite; PR 8 landed the bytes bucket only): same engine,
        # same CURRENT_QOS_KEY identity, quantum = 1 request so
        # backlogged keys alternate grants strictly
        if self._req_bucket is not None and limits.fair_keys:
            if self._fair_req is None or self._fair_req.bucket \
                    is not self._req_bucket:
                self._fair_req = DeficitRoundRobin(self._req_bucket,
                                                   quantum=1.0)
        else:
            self._fair_req = None
        if limits.max_concurrent is not None:
            if self._conc is None:
                self._conc = ConcurrencyLimiter(limits.max_concurrent,
                                                limits.max_queue)
            else:
                self._conc.configure(limits.max_concurrent,
                                     limits.max_queue)
        else:
            self._conc = None
        # retune per-scope buckets in place; drop them when disabled
        if limits.per_key_rps is None:
            self._key_buckets.clear()
        else:
            for b in self._key_buckets.values():
                b.configure(limits.per_key_rps, limits.per_key_rps)
        if limits.per_bucket_rps is None:
            self._bucket_buckets.clear()
        else:
            for b in self._bucket_buckets.values():
                b.configure(limits.per_bucket_rps, limits.per_bucket_rps)

    def update_limits(self, changes: dict) -> None:
        """Partial runtime update (admin `/v1/qos` POST): unknown keys
        raise, `null` clears a limit."""
        cur = self.limits.to_dict()
        for k, v in changes.items():
            if k not in cur:
                raise ValueError(f"unknown qos limit {k!r}")
            cur[k] = v
        lim = QosLimits(**cur)
        if lim.max_wait_s is None or lim.max_wait_s < 0:
            raise ValueError("max_wait_s must be >= 0")
        self.set_limits(lim)

    # ---- admission stages ----------------------------------------------

    def _record_shed(self, scope: str) -> None:
        self.counters.shed += 1
        by = self.counters.shed_by_scope
        by[scope] = by.get(scope, 0) + 1
        from ..utils.metrics import registry

        registry().inc("qos_shed_requests", scope=scope)

    def _record_wait(self, waited: float) -> None:
        if waited > 0:
            self.counters.queued_waits += 1
            self.counters.queued_seconds += waited

    def admit(self, api: str, nbytes: Optional[int] = None) -> "_Admission":
        """Global stage: `async with qos.admit("s3", nbytes): ...` —
        rps + declared-bytes buckets on enter, the concurrency slot
        held for the request's lifetime."""
        return _Admission(self, api, nbytes)

    async def _admit_request(self, lim: QosLimits) -> float:
        """Global request-rate draw. With `fair_keys` on and a request
        identity in hand (the S3/K2V frontends seed CURRENT_QOS_KEY
        from the request's CLAIMED key id before admission — fairness
        needs a stable queue key, not a verified one; enforcement
        still uses the verified identity in admit_scoped), contended
        grants drain through the per-key deficit round-robin: K
        backlogged keys each get ~1/K of the request rate instead of
        whoever queued first. The bounded-wait shed contract is
        unchanged — the estimated wait (bucket deficit plus the fair
        queue ahead of us) beyond max_wait_s sheds immediately.
        Returns seconds waited."""
        b = self._req_bucket
        fair = self._fair_req
        key = CURRENT_QOS_KEY.get() if fair is not None else None
        if fair is None or key is None:
            return await b.acquire(1.0, max_wait=lim.max_wait_s,
                                   scope="global")
        # shed bound priced at what ROUND-ROBIN will actually make this
        # arrival wait (its own key's queue + one rotation), not the
        # whole cross-key backlog — a flooding key throttles itself at
        # the bound while a fresh key still admits
        wait = b.wait_for(1.0 + fair.fair_wait_units(key))
        if wait > lim.max_wait_s:
            raise SlowDown(wait, "global")
        t0 = self.clock()
        await fair.submit(key, 1.0)
        return self.clock() - t0

    async def admit_scoped(self, key_id: Optional[str] = None,
                           bucket: Optional[str] = None) -> None:
        """Per-key / per-bucket request-rate stage (called once auth and
        bucket resolution are done)."""
        lim = self.limits
        kb = None
        try:
            if key_id is not None and lim.per_key_rps is not None:
                kb = self._scope_bucket(self._key_buckets, key_id,
                                        lim.per_key_rps)
                self._record_wait(await kb.acquire(
                    1.0, max_wait=lim.max_wait_s, scope="key"))
            if bucket is not None and lim.per_bucket_rps is not None:
                b = self._scope_bucket(self._bucket_buckets, bucket,
                                       lim.per_bucket_rps)
                try:
                    self._record_wait(await b.acquire(
                        1.0, max_wait=lim.max_wait_s, scope="bucket"))
                except SlowDown:
                    if kb is not None:
                        kb.refund(1.0)  # key grant unused: hand it back
                    raise
        except SlowDown as e:
            self._record_shed(e.scope)
            # attribute the shed to the identity it hit: both entities
            # recorded when known — "this key is being shed on this
            # bucket" is exactly what the operator is debugging
            if key_id is not None:
                self.counters.count_entity(self.counters.shed_by_key,
                                           key_id)
            if bucket is not None:
                self.counters.count_entity(self.counters.shed_by_bucket,
                                           bucket)
            raise

    def _scope_bucket(self, cache: dict, key: str,
                      rate: float) -> TokenBucket:
        b = cache.pop(key, None)
        if b is None:
            b = TokenBucket(rate, rate, clock=self.clock)
            if len(cache) >= SCOPE_CACHE_MAX:
                cache.pop(next(iter(cache)))
        cache[key] = b  # re-insert = move to MRU position
        return b

    async def shape_bytes(self, n: int, key: Optional[str] = None) -> None:
        """Mid-stream byte shaping for bodies whose length was unknown
        at admission (chunked uploads) and for block reads served from
        cache or store: never sheds — the request was already accepted
        and aborting it would waste the work done — it just slows the
        read loop to the configured byte rate.

        With `fair_keys` on and an identity in hand (the `key` argument
        or the request's CURRENT_QOS_KEY contextvar), contended draws
        go through the deficit round-robin so every active key gets an
        equal share of the drain instead of whoever queued first."""
        b = self._bytes_bucket
        if b is None or n <= 0:
            return
        self.counters.shaped_bytes += n
        self.counters.offered_bytes += n
        fair = self._fair
        if fair is not None:
            if key is None:
                key = CURRENT_QOS_KEY.get()
            if key is not None:
                await fair.submit(key, float(n))
                return
        wait = b.wait_for(float(n))
        b.tokens -= float(n)
        if wait > 0:
            await asyncio.sleep(wait)

    # ---- surface -------------------------------------------------------

    def state(self) -> dict:
        return {
            "limits": self.limits.to_dict(),
            "counters": self.counters.to_dict(),
            "in_flight": self._conc.active if self._conc else None,
            "queued": self._conc.queued if self._conc else None,
        }


class _Admission:
    """Context manager: rps + declared-bytes buckets on enter, the
    concurrency slot held until exit."""

    __slots__ = ("eng", "api", "nbytes", "_holding", "_t0")

    def __init__(self, eng: QosEngine, api: str, nbytes: Optional[int]):
        self.eng = eng
        self.api = api
        self.nbytes = nbytes
        self._holding = False

    async def __aenter__(self):
        eng, lim = self.eng, self.eng.limits
        from ..utils.metrics import registry

        # offered load is counted whether or not admission succeeds:
        # the gateway lease broker rebalances worker budgets by what
        # was ASKED of each worker, and a shedding worker is exactly
        # the one whose lease needs to grow
        if self.nbytes:
            eng.counters.offered_bytes += self.nbytes
        # stages debited so far, refunded when a LATER stage sheds —
        # a rejected request must not consume the budgets it passed
        debits: list = []
        try:
            if eng._req_bucket is not None:
                eng._record_wait(await eng._admit_request(lim))
                debits.append((eng._req_bucket, 1.0))
            if eng._bytes_bucket is not None and self.nbytes:
                eng._record_wait(await eng._bytes_bucket.acquire(
                    float(self.nbytes), max_wait=lim.max_wait_s,
                    scope="bytes"))
                debits.append((eng._bytes_bucket, float(self.nbytes)))
            if eng._conc is not None:
                await eng._conc.acquire(scope="concurrency")
                self._holding = True
        except SlowDown as e:
            for bucket, n in debits:
                bucket.refund(n)
            eng._record_shed(e.scope)
            raise
        self._t0 = time.perf_counter()
        eng.counters.admitted += 1
        registry().inc("qos_admitted_requests", api=self.api)
        return self

    async def __aexit__(self, *exc):
        if self._holding:
            self.eng._conc.release(time.perf_counter() - self._t0)
        return False
