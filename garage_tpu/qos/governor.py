"""Feedback-driven background-work governor.

The fixed `Tranquilizer` tranquility of resync/scrub workers is blind
to foreground latency: a deep-scrub storm keeps hammering the disks and
event loop while users wait, and an idle cluster still crawls through
repair at the configured trickle. This worker closes the loop:

  sample    per-interval mean of foreground request latency — the
            `api_request_duration_seconds` series (every S3/K2V/admin
            request) plus the foreground-priority slice of
            `rpc_request_duration_seconds` (net dispatch path; resync
            and scrub RPCs travel PRIO_BACKGROUND and are excluded so
            the governor never chases its own tail).
  smooth    EWMA so one slow request doesn't whipsaw the workers.
  control   integral controller on `pressure` in [0, 1]: latency above
            target pushes pressure up (background yields), below target
            bleeds it off (background sprints); no foreground traffic
            at all decays it toward 0.
  actuate   pressure maps linearly onto each worker's tranquility
            range: scrub tranquility in [scrub_min, scrub_max]
            (a duration multiplier, repair.py) and resync tranquility
            in [resync_min, resync_max] (an inter-item delay in
            seconds, resync.py).

While enabled the governor OWNS those two tranquilities — with one
exception: an explicit operator `worker set resync-tranquility` /
`scrub-tranquility` places a manual hold on that knob (operators
outrank the loop). Retune the *bounds* via admin `/v1/qos`, disable
the whole loop with `worker set qos-governor 0`, or re-enable with
`... 1` (which also clears manual holds).
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from ..utils.background import Throttled, Worker, WorkerInfo
from ..utils.metrics import registry

log = logging.getLogger("garage_tpu.qos")


def foreground_latency_totals() -> tuple[int, float]:
    """(count, total_seconds) of foreground work since process start."""
    reg = registry()
    c1, t1 = reg.totals("api_request_duration_seconds")
    c2, t2 = reg.totals("rpc_request_duration_seconds", bg="0")
    return c1 + c2, t1 + t2


class GovernorWorker(Worker):
    name = "qos governor"

    # integral gain per step, and the cap on one step's |pressure| move
    GAIN = 0.25
    MAX_STEP = 0.5
    # pressure decay per idle interval (no foreground samples at all)
    IDLE_DECAY = 0.15
    EWMA_ALPHA = 0.3
    # pressure push per queued writer at the block byte-semaphore (a
    # LEADING signal: writers park there before any latency sample can
    # show the overload), capped at one MAX_STEP per interval
    QUEUE_GAIN = 0.1
    QUEUE_REF_DEPTH = 5  # depth at which the queue signal saturates
    # pressure push from the resync/rebalance backlog while foreground
    # traffic is active: a deep backlog means rebalance pushes/fetches
    # are competing with users for the same links and disks, so
    # background work yields BEFORE the latency EWMA shows the damage.
    # When the cluster is foreground-idle the idle decay wins instead
    # and the rebalance sprints.
    RESYNC_GAIN = 0.3  # max push per interval, at backlog saturation
    RESYNC_REF_BACKLOG = 256.0  # `[qos] resync_backlog_ref`
    # pressure maps onto the table syncers' per-partition sleep too: a
    # layout change triggers an anti-entropy round of every table on
    # every node at once, and unthrottled rounds were the dominant
    # foreground-p99 cost of a resize. Default for the
    # `[table] sync_tranquility_max` knob (ctor overrides).
    TABLE_SYNC_TRANQ_MAX = 0.05  # s/partition at pressure 1.0
    # lsm compaction pacing: seconds of sleep between merge steps at
    # pressure 1.0 (db/lsm.py LsmMaintenanceWorker). A merge step is a
    # burst of disk+CPU, so it yields harder than per-item work does.
    LSM_COMPACT_TRANQ_MAX = 5.0
    # cache-tier hint prefetch pacing (block/cache_tier.py, ISSUE 18):
    # seconds of sleep before each background prefetch decode at
    # pressure 1.0 — a prefetch is a speculative gather+decode, so it
    # yields to foreground latency like resync does
    PREFETCH_TRANQ_MAX = 2.0

    def __init__(self, garage, interval: float = 2.0,
                 target_latency: float = 0.05,
                 scrub_range: tuple[float, float] = (1.0, 30.0),
                 resync_range: tuple[float, float] = (0.0, 2.0),
                 sample_fn: Optional[Callable[[], tuple[int, float]]] = None,
                 queue_depth_fn: Optional[Callable[[], int]] = None,
                 resync_backlog_fn: Optional[Callable[[], int]] = None,
                 resync_backlog_ref: Optional[float] = None,
                 table_sync_tranq_max: Optional[float] = None):
        self.garage = garage
        self.interval = interval
        self.target_latency = target_latency
        self.scrub_range = scrub_range
        self.resync_range = resync_range
        self.sample_fn = sample_fn or foreground_latency_totals
        self.queue_depth_fn = queue_depth_fn
        self.resync_backlog_fn = resync_backlog_fn
        self.resync_backlog_ref = float(resync_backlog_ref
                                        or self.RESYNC_REF_BACKLOG)
        self.table_sync_tranq_max = float(
            self.TABLE_SYNC_TRANQ_MAX if table_sync_tranq_max is None
            else table_sync_tranq_max)
        self.enabled = True
        self.pressure = 0.0
        self.ewma: Optional[float] = None
        self.last_queue_depth = 0
        self.last_resync_backlog = 0
        self._last: Optional[tuple[int, float]] = None
        self.adjustments = 0

    def _queue_depth(self) -> int:
        """Writers parked at the block manager's byte-semaphore."""
        if self.queue_depth_fn is not None:
            return self.queue_depth_fn()
        bm = getattr(self.garage, "block_manager", None)
        sem = getattr(bm, "_ram_sem", None)
        return sem.queue_depth() if sem is not None else 0

    def _resync_backlog(self) -> int:
        """Blocks queued for resync and due NOW — during a cluster
        resize this IS the rebalance backlog. Future-due entries
        (error backoff, breaker deferrals) are excluded: they are not
        competing with foreground traffic."""
        if self.resync_backlog_fn is not None:
            return self.resync_backlog_fn()
        bm = getattr(self.garage, "block_manager", None)
        resync = getattr(bm, "resync", None)
        qlen = getattr(resync, "due_len", None) \
            or getattr(resync, "queue_len", None)
        # getattr-soft: governor tests run against stub resyncs
        return qlen() if callable(qlen) else 0

    # ---- control step (synchronous, unit-testable) ---------------------

    def step(self) -> None:
        count, total = self.sample_fn()
        if self._last is None:
            self._last = (count, total)
            return
        dc = count - self._last[0]
        dt = total - self._last[1]
        self._last = (count, total)
        if dc > 0:
            lat = dt / dc
            self.ewma = (lat if self.ewma is None
                         else self.ewma + self.EWMA_ALPHA * (lat - self.ewma))
            err = self.ewma / self.target_latency - 1.0
            move = max(-self.MAX_STEP, min(self.MAX_STEP, self.GAIN * err))
            self.pressure = max(0.0, min(1.0, self.pressure + move))
        else:
            # cluster is foreground-idle: let background work sprint
            self.pressure = max(0.0, self.pressure - self.IDLE_DECAY)
        # queue-depth signal (ROADMAP "governor signal breadth"): byte-
        # semaphore waiters mean the write path is ALREADY saturated,
        # even if the latency EWMA hasn't caught up — push background
        # work back before users feel it
        self.last_queue_depth = depth = self._queue_depth()
        if depth > 0:
            move = min(self.MAX_STEP,
                       self.QUEUE_GAIN * min(depth, self.QUEUE_REF_DEPTH))
            self.pressure = min(1.0, self.pressure + move)
        # resync-backlog signal (ISSUE 6): rebalance yields to
        # foreground p99 while users are active; with no foreground
        # traffic the idle decay above already lets it sprint
        self.last_resync_backlog = backlog = self._resync_backlog()
        if backlog > 0 and dc > 0:
            move = min(self.MAX_STEP,
                       self.RESYNC_GAIN
                       * min(backlog / self.resync_backlog_ref, 1.0))
            self.pressure = min(1.0, self.pressure + move)
        self._apply()

    def _apply(self) -> None:
        bm = getattr(self.garage, "block_manager", None)
        if bm is None:
            return
        u = self.pressure
        # a manual `worker set <x>-tranquility` holds that knob until
        # the governor is explicitly re-enabled (`worker set
        # qos-governor 1` clears the holds) — operators outrank the
        # control loop
        if not getattr(bm.resync, "tranquility_manual", False):
            lo, hi = self.resync_range
            bm.resync.tranquility = lo + u * (hi - lo)
        sw = getattr(bm, "scrub_worker", None)
        if sw is not None and not getattr(sw.state, "tranquility_manual",
                                          False):
            lo, hi = self.scrub_range
            # in-memory only: the scrub worker persists its state at
            # each batch/pass boundary anyway, and a persister write per
            # governor tick would be pure write amplification
            sw.state.tranquility = lo + u * (hi - lo)
        all_tables = getattr(self.garage, "all_tables", None)
        if callable(all_tables):
            tranq = u * self.table_sync_tranq_max
            for t in all_tables():
                syncer = getattr(t, "syncer", None)
                if syncer is not None:
                    syncer.tranquility = tranq
        # lsm compaction yields to foreground latency the same way the
        # table syncers do (db/lsm.py LsmMaintenanceWorker)
        lm = getattr(self.garage, "lsm_maintenance", None)
        if lm is not None:
            lm.tranquility = u * self.LSM_COMPACT_TRANQ_MAX
        # cache-tier hint prefetch yields like resync: speculative
        # decodes must never compete with the foreground reads they
        # exist to speed up
        tier = getattr(bm, "cache_tier", None)
        if tier is not None:
            tier.prefetch_tranquility = u * self.PREFETCH_TRANQ_MAX
        self.adjustments += 1
        registry().inc("qos_governor_pressure", self.pressure)

    # ---- worker protocol -----------------------------------------------

    async def work(self):
        if self.enabled:
            self.step()
        return Throttled(self.interval)

    async def wait_for_work(self):
        import asyncio

        await asyncio.sleep(self.interval)

    def info(self) -> WorkerInfo:
        ewma_ms = f"{self.ewma * 1000:.1f}ms" if self.ewma else "-"
        return WorkerInfo(
            name=self.name,
            progress=(f"pressure {self.pressure:.2f}, ewma {ewma_ms}"
                      + ("" if self.enabled else " (disabled)")),
        )

    def state(self) -> dict:
        return {
            "enabled": self.enabled,
            "pressure": round(self.pressure, 4),
            "ewma_latency_s": (round(self.ewma, 6)
                               if self.ewma is not None else None),
            "queue_depth": self.last_queue_depth,
            "resync_backlog": self.last_resync_backlog,
            "target_latency_s": self.target_latency,
            "scrub_range": list(self.scrub_range),
            "resync_range": list(self.resync_range),
            "adjustments": self.adjustments,
        }
