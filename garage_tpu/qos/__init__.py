"""QoS admission control: what work enters the node, how fast
background work runs.

No reference analogue — the reference Garage ships request *priorities*
(net/message.rs PRIO bits, reproduced in `garage_tpu/net/`) but nothing
stands between a burst of S3 PUTs (or a deep-scrub storm) and unbounded
queueing. This subsystem adds the three missing pieces:

  limiters   composable token buckets (requests/s, bytes/s) and a
             bounded concurrency gate, enforced at the API layer
             per-global / per-key / per-bucket; a request whose bounded
             wait would be exceeded is SHED with the S3-correct
             `503 SlowDown` + `Retry-After` instead of queueing.
  governor   a feedback loop sampling foreground API/RPC latency (EWMA
             over utils/metrics series) that dynamically retunes the
             Tranquilizer tranquility of resync and scrub workers —
             background repair yields when users are waiting and
             sprints when the cluster is idle (the adaptive-concurrency
             shape inference-serving stacks use to protect p99).
  surface    admitted/shed/queued counters in the metrics registry,
             runtime get/set of every limit via the admin HTTP API
             (`/v1/qos`), and a bench.py scenario.
"""

from .limiter import (ConcurrencyLimiter, QosEngine, SlowDown,  # noqa: F401
                      TokenBucket)
from .governor import GovernorWorker  # noqa: F401
