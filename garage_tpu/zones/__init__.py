"""Zones as a first-class subsystem (ISSUE 16).

The reference system is geo-distributed object storage: zones are the
failure domain the layout spreads replicas across (PAPER.md,
`rpc/layout/assign.py`). This package makes that domain visible at
runtime instead of being only a placement label:

- `ZoneHealth` (health.py) derives per-zone state — up / degraded /
  partitioned — from the peering data every node already gossips, and
  backs the admin `GET /v1/zones` endpoint.
- The zone-aware quorum strategy lives in `rpc/rpc_helper.py`
  (`RequestStrategy.consistency` / zone-span write verification); the
  per-zone cache-tier ring in `block/cache_tier.py`; the
  `partition_zone` chaos fault in `chaos/injector.py`. This package
  holds the shared zone-membership logic they all consume.
"""

from .health import ZoneHealth, ZoneState, layout_zone_resolver

__all__ = ["ZoneHealth", "ZoneState", "layout_zone_resolver"]
