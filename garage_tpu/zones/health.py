"""Per-zone health derived from peering state.

Dynamo-style zone awareness (DeCandia et al., SOSP'07) starts with
knowing which failure domains are reachable. Every node already tracks
per-peer liveness three ways — the connection state machine
(net/peering.py `_Peer.state`), the consecutive-failed-ping counter,
and the circuit breakers in `PeerHealthTracker` — so zone health is a
pure derivation over data the system gossips anyway; no new protocol.

A node counts as DOWN when any of the three signals says so: it is not
connected, its breaker is open, or it has missed two consecutive pings
(half the disconnect threshold — pings fail well before the peering
layer tears the link down, and a severed link can flap through
reconnect-then-die cycles where the conn state alone looks healthy).

Zone state rolls up its member nodes:

- UP           every member node is up
- DEGRADED     some but not all member nodes are down
- PARTITIONED  every member node is down (the whole failure domain is
               unreachable — from THIS observer's side of the cut)

The local node is always up from its own point of view, so the local
zone can never report PARTITIONED — matching the drill's expectation
that each surviving node sees the severed zone partitioned while the
severed zone's own nodes see everyone ELSE that way.
"""

from __future__ import annotations

from enum import Enum
from typing import Callable, Optional

# A node is suspected down after this many consecutive failed pings —
# half of peering's FAILED_PING_THRESHOLD (4), because zone state must
# move before the peering layer gives up on the link entirely.
SUSPECT_FAILED_PINGS = 2


class ZoneState(Enum):
    UP = "up"
    DEGRADED = "degraded"
    PARTITIONED = "partitioned"


def layout_zone_resolver(layout_manager) -> Callable[[bytes], Optional[str]]:
    """node_id -> zone name per the CURRENT layout version (None when
    the node has no storage role there). The chaos injector's
    `partition_zone` fault and the cache tier's per-zone ring both key
    off this — one shared definition of "which zone is node X in"."""

    def resolve(node: bytes) -> Optional[str]:
        role = layout_manager.history.current().node_role(node)
        if role is None or not role.zone:
            return None
        return role.zone

    return resolve


class ZoneHealth:
    """Zone-state tracker hung off `System` (rpc/system.py).

    Stateless by design: every read derives from the live layout +
    peering structures, so there is no refresh loop to schedule and no
    staleness beyond the peering ping interval itself — `GET /v1/zones`
    reflects a zone partition as soon as the pings that detect it fail.
    """

    def __init__(self, system):
        self.system = system

    # ---- membership -----------------------------------------------------

    def zone_of(self, node: bytes) -> Optional[str]:
        role = self.system.layout_manager.history.current().node_role(node)
        if role is None or not role.zone:
            return None
        return role.zone

    def local_zone(self) -> Optional[str]:
        return self.zone_of(self.system.id)

    def zone_nodes(self) -> dict[str, list[bytes]]:
        """zone -> storage nodes of the current layout version, sorted
        for stable output."""
        layout = self.system.layout_manager.history.current()
        zones: dict[str, list[bytes]] = {}
        for node in layout.storage_nodes():
            role = layout.node_role(node)
            if role is None or not role.zone:
                continue
            zones.setdefault(role.zone, []).append(node)
        for members in zones.values():
            members.sort()
        return zones

    # ---- liveness -------------------------------------------------------

    def node_down(self, node: bytes) -> bool:
        system = self.system
        if node == system.id:
            return False
        if not system.is_up(node):
            return True
        peering = system.peering
        peer = peering.peers.get(node)
        if peer is not None and peer.failed_pings >= SUSPECT_FAILED_PINGS:
            return True
        return peering.health.breaker_state(node) == "open"

    # ---- rollup ---------------------------------------------------------

    def zone_state(self, zone: str) -> Optional[ZoneState]:
        members = self.zone_nodes().get(zone)
        if not members:
            return None
        down = sum(1 for n in members if self.node_down(n))
        if down == 0:
            return ZoneState.UP
        if down == len(members):
            return ZoneState.PARTITIONED
        return ZoneState.DEGRADED

    def partitioned_zones(self) -> set[str]:
        return {z for z, st in self._states().items()
                if st == ZoneState.PARTITIONED}

    def _states(self) -> dict[str, ZoneState]:
        out = {}
        for zone, members in self.zone_nodes().items():
            down = sum(1 for n in members if self.node_down(n))
            if down == 0:
                out[zone] = ZoneState.UP
            elif down == len(members):
                out[zone] = ZoneState.PARTITIONED
            else:
                out[zone] = ZoneState.DEGRADED
        return out

    def snapshot(self) -> dict:
        """The `GET /v1/zones` body: per-zone state + member liveness,
        plus which zone the reporting node sits in (zone state is
        observer-relative by nature — a severed zone sees the rest of
        the world partitioned, not itself)."""
        zones = []
        for zone, members in sorted(self.zone_nodes().items()):
            down = [n for n in members if self.node_down(n)]
            if not down:
                state = ZoneState.UP
            elif len(down) == len(members):
                state = ZoneState.PARTITIONED
            else:
                state = ZoneState.DEGRADED
            zones.append({
                "zone": zone,
                "state": state.value,
                "nodes": len(members),
                "nodesUp": len(members) - len(down),
                "downNodes": [n.hex() for n in down],
            })
        return {"localZone": self.local_zone(), "zones": zones}
