"""Multi-chip data-plane parallelism: meshes, sharded encode/scrub/repair.

The scale axes of the reference are nodes in a TCP mesh (SURVEY.md §2.11);
here the intra-host scale axis is a `jax.sharding.Mesh` over TPU chips:

  dp  — batch of stripes/blocks, embarrassingly parallel
  tp  — within a stripe: byte-columns for encode (GF matmul is per
        byte-position), whole shards for hashing; XLA inserts the
        all_to_all between the two layouts and psums for global stats

No reference analogue — Garage's data plane is single-threaded-per-block
CPU (src/block/manager.rs); this is the TPU-native replacement.
"""

from .mesh import (  # noqa: F401
    data_plane_mesh,
    make_put_step,
    make_repair_step,
    make_scrub_step,
)
