"""Device meshes and the sharded block data-plane steps.

Everything here follows the annotate-and-let-XLA-partition recipe: build a
Mesh, place `NamedSharding`s on inputs/outputs, add
`with_sharding_constraint` at layout changes, and let the SPMD partitioner
insert the collectives (all_to_all between byte-split and shard-split
layouts, all_gather for the k-contraction in scrub, psum for global
counters). No hand-written collectives — the steps are ordinary jitted
functions that also run unsharded on one chip.

Shapes (all static under jit):
  stripe batch: (B, k, S) uint8 — B stripes, k data shards, S bytes/shard
  parity:       (B, m, S) uint8
  hashes:       (B, n, 32) uint8 — BLAKE3 of each of the n = k+m shards

Divisibility: dp must divide B and tp must divide S (the byte-split
layout always shards the byte axis). The whole-shard layout shards the
n = k+m axis when tp divides n; otherwise it falls back to sharding S
there too (e.g. RS(2,1) n=3 on tp=2, or RS(10,4) n=14 on tp=4) — the
all_to_all between layouts disappears and hashing partitions over the
byte axis instead (chunk compressions are independent in S, the tree
reduction crosses tp via XLA collectives).
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops import gf256, rs, treehash


def data_plane_mesh(n_devices: int | None = None, tp: int | None = None):
    """(dp, tp) mesh over the first `n_devices` devices."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if tp is None:
        tp = 2 if n % 2 == 0 and n > 1 else 1
    if n % tp:
        raise ValueError(f"tp={tp} does not divide device count {n}")
    mesh_devs = np.asarray(devs).reshape(n // tp, tp)
    return Mesh(mesh_devs, ("dp", "tp"))


def _sh(mesh, *spec):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))


def bytes_sharding(mesh):
    """The byte-split (B, n, S) input sharding — dp over stripes, tp
    over the byte axis. Public so the feeder's staged h2d can
    device_put batches directly into the mesh layout (the transfer
    itself fans out across chips instead of landing on chip 0)."""
    return _sh(mesh, "dp", None, "tp")


def mats_sharding(mesh):
    """Sharding of the per-stripe bit-matrix stack (B, 8k, 8r) for the
    pattern-as-data steps: dp over stripes, the (tiny) matrix axes
    replicated. Public so the feeder's staged h2d can device_put the
    matrices straight into the mesh layout alongside the shard bytes."""
    return _sh(mesh, "dp", None, None)


def _layouts(mesh, n: int, shard_len: int):
    """(bytes_sh, shards_sh, n_sharded) for a (B, n, S) stripe batch.
    Validates tp | S; shards the n axis in the whole-shard layout only
    when tp | n, else keeps sharding S (see module docstring)."""
    tp = mesh.shape["tp"]
    if shard_len % tp:
        raise ValueError(
            f"tp={tp} must divide shard_len={shard_len} (byte-split layout)")
    bytes_sh = _sh(mesh, "dp", None, "tp")
    if n % tp == 0:
        return bytes_sh, _sh(mesh, "dp", "tp", None), True
    return bytes_sh, bytes_sh, False


def _hash_all_shards(shards, n_chunks: int):
    """(B, n, S) uint8 -> (B, n, 32) uint8 BLAKE3 digests (full shards)."""
    import jax.numpy as jnp

    b, n, s = shards.shape
    rows = shards.reshape(b * n, s)
    lengths = jnp.full((b * n,), s, dtype=jnp.int32)
    cvs = treehash.hash_rows(rows, lengths, n_chunks)  # (B*n, 8) u32
    # u32 -> 4 little-endian bytes, matching the host digest encoding
    shifts = jnp.arange(4, dtype=jnp.uint32) * 8
    by = ((cvs[..., None] >> shifts) & 0xFF).astype(jnp.uint8)
    return by.reshape(b, n, 32)


@functools.lru_cache(maxsize=None)
def make_put_step(mesh, k: int, m: int, shard_len: int):
    """Jitted PUT data plane: stripes -> (parity, per-shard BLAKE3).

    This is the TPU replacement for the reference's per-block CPU work in
    the S3 PUT hot loop (src/api/s3/put.rs:378-530 stages 2-4: hashing +
    per-block checksum; plus the erasure encode the reference lacks).
    """
    import jax
    import jax.numpy as jnp

    if shard_len % treehash.CHUNK_LEN:
        raise ValueError(f"shard_len must be a multiple of {treehash.CHUNK_LEN}")
    n_chunks = shard_len // treehash.CHUNK_LEN
    parity_bits = gf256.bitmat_t_for(rs.parity_matrix(k, m))
    bytes_sh, shards_sh, _ = _layouts(mesh, k + m, shard_len)

    def step(data):
        # encode in byte-split layout (local matmul per byte-column)
        data = jax.lax.with_sharding_constraint(data, bytes_sh)
        parity = gf256.bit_matmul_apply(parity_bits, data)
        allsh = jnp.concatenate([data, parity], axis=1)  # (B, n, S)
        # reshard to whole-shard layout for hashing (XLA: all_to_all)
        allsh = jax.lax.with_sharding_constraint(allsh, shards_sh)
        hashes = _hash_all_shards(allsh, n_chunks)
        return parity, hashes

    return jax.jit(
        step,
        in_shardings=bytes_sh,
        out_shardings=(bytes_sh, shards_sh),
    )


@functools.lru_cache(maxsize=None)
def make_encode_step(mesh, k: int, m: int, shard_len: int):
    """Jitted parity-only encode: (B, k, S) data -> (B, m, S) parity,
    dp-sharded over stripes and tp-sharded over the byte axis (local
    matmul per byte-column, no cross-chip collective needed). This is
    the feeder's multi-chip batch-sharding step: unlike make_put_step
    it skips device hashing, because the live PUT path advances its
    ETag-MD5 chains host-side and the staged backend reads parity back
    while the next batch computes."""
    import jax

    parity_bits = gf256.bitmat_t_for(rs.parity_matrix(k, m))
    bytes_sh, _, _ = _layouts(mesh, k + m, shard_len)

    def step(data):
        data = jax.lax.with_sharding_constraint(data, bytes_sh)
        return gf256.bit_matmul_apply(parity_bits, data)

    return jax.jit(step, in_shardings=bytes_sh, out_shardings=bytes_sh)


@functools.lru_cache(maxsize=None)
def make_parity_check_step(mesh, k: int, m: int, shard_len: int):
    """Jitted per-stripe parity consistency: (B, k+m, S) stored shards
    -> (B,) bool, True when re-derived parity equals the stored parity
    rows. The deep-scrub feeder op's multi-chip route; zero-padded
    stripes come out True (zero data encodes to zero parity — linear
    code) and are sliced away by the caller."""
    import jax
    import jax.numpy as jnp

    parity_bits = gf256.bitmat_t_for(rs.parity_matrix(k, m))
    bytes_sh, _, _ = _layouts(mesh, k + m, shard_len)

    def step(stripes):
        stripes = jax.lax.with_sharding_constraint(stripes, bytes_sh)
        parity2 = gf256.bit_matmul_apply(parity_bits, stripes[:, :k, :])
        return jnp.all(parity2 == stripes[:, k:, :], axis=(1, 2))

    return jax.jit(step, in_shardings=bytes_sh, out_shardings=_sh(mesh, "dp"))


@functools.lru_cache(maxsize=None)
def make_gf_apply_step(mesh, k: int, rows: int, shard_len: int):
    """Jitted pattern-as-data GF apply: per-stripe (8k, 8·rows)
    bit-matrices (dp-sharded, tiny, replicated across tp) applied to a
    (B, k, S) shard stack (dp over stripes, tp over the byte axis —
    the contraction is per byte-position, so no cross-chip collective).
    This is the feeder's multi-chip decode/repair route: one compiled
    program per SHAPE serves every erasure pattern, because which
    shards survived lives in the matrix DATA, not the trace."""
    import jax

    tp = mesh.shape["tp"]
    if shard_len % tp:
        raise ValueError(
            f"tp={tp} must divide shard_len={shard_len} (byte-split layout)")
    bytes_sh = _sh(mesh, "dp", None, "tp")

    def step(mats_t, shards):
        shards = jax.lax.with_sharding_constraint(shards, bytes_sh)
        return gf256.bit_matmul_apply_batched(mats_t, shards)

    return jax.jit(step, in_shardings=(mats_sharding(mesh), bytes_sh),
                   out_shardings=bytes_sh)


@functools.lru_cache(maxsize=None)
def make_scrub_step(mesh, k: int, m: int, shard_len: int):
    """Jitted scrub: verify every stored shard's hash + parity consistency.

    Returns (per-shard corrupt mask (B, n) bool, global corrupt count).
    Replaces the reference's one-block-at-a-time scrub read+rehash loop
    (src/block/repair.rs:169-528) with a batched device pass; the global
    count is a psum across the whole mesh.
    """
    import jax
    import jax.numpy as jnp

    if shard_len % treehash.CHUNK_LEN:
        raise ValueError(f"shard_len must be a multiple of {treehash.CHUNK_LEN}")
    n_chunks = shard_len // treehash.CHUNK_LEN
    parity_bits = gf256.bitmat_t_for(rs.parity_matrix(k, m))
    bytes_sh, shards_sh, n_sharded = _layouts(mesh, k + m, shard_len)
    mask_sh = _sh(mesh, "dp", "tp") if n_sharded else _sh(mesh, "dp")

    def step(shards, expected_hashes):
        shards = jax.lax.with_sharding_constraint(shards, shards_sh)
        hashes = _hash_all_shards(shards, n_chunks)
        hash_bad = jnp.any(hashes != expected_hashes, axis=-1)  # (B, n)
        # parity re-derivation: contraction over k crosses the tp axis in
        # shard-split layout; the reshard is XLA's to insert.
        # Only meaningful when every data shard hash-checks: recomputing
        # parity from a corrupt data shard mismatches ALL stored parity
        # rows, which would smear one bad data shard over m healthy
        # parity shards and make the mask useless for repair planning.
        data = jax.lax.with_sharding_constraint(shards[:, :k, :], bytes_sh)
        parity2 = gf256.bit_matmul_apply(parity_bits, data)
        data_clean = ~jnp.any(hash_bad[:, :k], axis=1)  # (B,)
        parity_bad = (
            jnp.any(parity2 != shards[:, k:, :], axis=-1) & data_clean[:, None]
        )  # (B, m)
        bad = hash_bad | jnp.concatenate(
            [jnp.zeros((shards.shape[0], k), dtype=bool), parity_bad], axis=1
        )
        return bad, jnp.sum(bad, dtype=jnp.int32)

    return jax.jit(
        step,
        in_shardings=(shards_sh, shards_sh),
        out_shardings=(mask_sh, _sh(mesh)),
    )


@functools.lru_cache(maxsize=None)
def _repair_apply_step(mesh, n: int, shard_len: int):
    """Shape-keyed inner jit for make_repair_step: (mat_bits, surviving)
    -> (rebuilt, hashes). Keyed on mesh/shape facts only, never on the
    erasure pattern — the repair matrix is a traced operand."""
    import jax

    n_chunks = shard_len // treehash.CHUNK_LEN
    bytes_sh, _, _ = _layouts(mesh, n, shard_len)

    def step(mat_bits, surviving):
        surviving = jax.lax.with_sharding_constraint(surviving, bytes_sh)
        rebuilt = gf256.bit_matmul_apply(mat_bits, surviving)  # (B, |missing|, S)
        hashes = _hash_all_shards(rebuilt, n_chunks)
        return rebuilt, hashes

    return jax.jit(step, in_shardings=(_sh(mesh), bytes_sh))


def make_repair_step(
    mesh, k: int, m: int, present: tuple[int, ...], missing: tuple[int, ...], shard_len: int
):
    """Jitted repair: rebuild `missing` shards from the k `present` ones
    and return them with fresh hashes. Degraded-read/resync math: where
    the reference re-fetches whole replicas (src/block/resync.rs:354-505),
    erasure mode decodes any k of n on device.

    The per-pattern repair matrix rides as a tensor operand into a
    shape-keyed jitted apply: every (present, missing) pattern of the
    same size shares ONE compiled program, where the old per-pattern
    lru_cache compiled (and pinned a step for) each of the
    O(n choose k) patterns a degraded cluster can walk through."""
    import jax

    if shard_len % treehash.CHUNK_LEN:
        raise ValueError(f"shard_len must be a multiple of {treehash.CHUNK_LEN}")
    mat_bits = gf256.bitmat_t_for(rs.repair_matrix(k, m, present, missing))
    mat_bits = jax.device_put(mat_bits, _sh(mesh))
    apply_step = _repair_apply_step(mesh, k + m, shard_len)

    def step(surviving):  # (B, k, S) rows `present` in ascending order
        return apply_step(mat_bits, surviving)

    return step
