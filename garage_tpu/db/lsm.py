"""Log-structured merge engine for metadata at millions of keys.

No reference analogue (the reference ships LMDB + sqlite behind the
same `db/` seam; this build's third engine targets the workload the
ROADMAP names "Metadata at millions of objects"): sqlite's B-tree pays
a read-modify-write per UPSERT, while S3 metadata at scale is
insert-mostly with long ordered scans — exactly the LSM sweet spot.

Layout on disk (one directory per db):

  wal.log            committed-transaction log: every commit appends one
                     length+crc framed msgpack batch; replayed on open.
                     Truncated only at flush-all, so every WAL record is
                     strictly newer than every segment.
  MANIFEST           msgpack: per-tree segment lists (newest first),
                     per-tree live-key counts, next segment id.
                     Rewritten atomically (tmp + rename) at every flush
                     and compaction.
  seg-<id>.sst       immutable sorted run: data blocks (msgpack entry
                     lists, ~32 KiB) + footer with a sparse first-key
                     block index and a bloom filter. Tombstones are
                     stored (value=None) so newer runs mask older ones;
                     a merge that includes the tree's oldest run drops
                     them for good.

Concurrency model matches the other engines: all calls arrive under the
Db RLock, synchronous. Durability: commits are flushed to the OS always
(a crashed *process* loses nothing); `fsync=True` (metadata_fsync)
additionally fsyncs the WAL per commit and segments/manifest per flush,
matching sqlite's synchronous=FULL semantics.

Snapshot iterators: `iter_snapshot()` freezes the active memtable
(pointer swap, not copy) and takes refcounts on the current segment
list; flushes and compactions proceed underneath while the iterator
streams a stable view. Compaction defers file unlink until the last
reader releases its ref (POSIX would allow unlinking open files, but
the refcount also keeps Windows/tests honest and bounds disk use
explicitly).

Background maintenance: `LsmMaintenanceWorker` (spawned by Garage when
db_engine="lsm") runs one `compact_once()` step per tick off the event
loop, pacing itself by `tranquility` — the qos governor maps foreground
pressure onto it exactly like resync/scrub, so a compaction storm
yields to user latency and sprints when the node is idle. Inline
backpressure: a flush that leaves a tree with an excessive segment
count runs merges synchronously so an idle-loop-less process (bench,
CLI) cannot accumulate unbounded runs.
"""

from __future__ import annotations

import bisect
import logging
import os
import struct
import threading
import zlib
from typing import Iterator, Optional, Tuple

import msgpack

from .db import PREV_UNKNOWN

log = logging.getLogger("garage_tpu.db.lsm")

# ---- tuning constants (see README "Metadata at scale") -----------------

BLOCK_BYTES = 16 * 1024          # target data-block size in a segment
BLOOM_BITS_PER_KEY = 10          # ~1% false positives at K=5
BLOOM_K = 5
MEMTABLE_MAX_BYTES = 8 * 1024 * 1024   # flush-all threshold (sum of trees)
TIER_FANIN = 4                   # merge when a tier holds this many runs
MAX_SEGMENTS_HARD = 24           # inline-compact above this (backpressure)
BLOCK_CACHE_BLOCKS = 1024         # decoded blocks cached engine-wide

_MAGIC = b"GTLSM1\x00\x00"
_WAL_HDR = struct.Struct("<II")  # payload length, crc32


class Bloom:
    __slots__ = ("nbits", "bits")

    def __init__(self, nbits: int, bits: bytearray):
        self.nbits = nbits
        self.bits = bits

    @classmethod
    def build(cls, keys) -> "Bloom":
        nbits = max(64, len(keys) * BLOOM_BITS_PER_KEY)
        b = cls(nbits, bytearray((nbits + 7) // 8))
        for k in keys:
            h1 = zlib.crc32(k)
            h2 = zlib.adler32(k) | 1
            for i in range(BLOOM_K):
                pos = (h1 + i * h2) % nbits
                b.bits[pos >> 3] |= 1 << (pos & 7)
        return b

    def might_contain(self, key: bytes) -> bool:
        h1 = zlib.crc32(key)
        h2 = zlib.adler32(key) | 1
        nbits = self.nbits
        bits = self.bits
        for i in range(BLOOM_K):
            pos = (h1 + i * h2) % nbits
            if not bits[pos >> 3] >> (pos & 7) & 1:
                return False
        return True


class Segment:
    """One immutable sorted run, mmap-free random access via the sparse
    index. `refs` counts the manifest (1) plus live snapshot iterators;
    `drop()` marks it dead and the last `release()` unlinks it."""

    __slots__ = ("path", "seg_id", "f", "index", "bloom", "count",
                 "min_key", "max_key", "data_bytes", "refs", "_dead",
                 "_lock")

    def __init__(self, path: str, seg_id: int):
        self.path = path
        self.seg_id = seg_id
        self.f = open(path, "rb")
        self.f.seek(-16, os.SEEK_END)
        tail = self.f.read(16)
        if tail[8:] != _MAGIC:
            raise ValueError(f"bad segment magic in {path}")
        (flen,) = struct.unpack("<q", tail[:8])
        self.f.seek(-16 - flen, os.SEEK_END)
        foot = msgpack.unpackb(self.f.read(flen), raw=True)
        # index: [[first_key, offset, length], ...] ascending
        self.index = [(bytes(k), o, ln) for k, o, ln in foot[b"index"]]
        self.bloom = Bloom(foot[b"nbits"], bytearray(foot[b"bloom"]))
        self.count = foot[b"count"]
        self.min_key = bytes(foot[b"min"])
        self.max_key = bytes(foot[b"max"])
        self.data_bytes = foot[b"bytes"]
        self.refs = 1
        self._dead = False
        self._lock = threading.Lock()

    # refcounting -----------------------------------------------------

    def acquire(self) -> "Segment":
        with self._lock:
            self.refs += 1
        return self

    def release(self) -> None:
        with self._lock:
            self.refs -= 1
            gone = self.refs == 0 and self._dead
        if gone:
            self._close_unlink()

    def drop(self) -> None:
        """Release the manifest's reference (the constructor's ref=1)
        and mark the segment dead; the file disappears once the last
        snapshot reader releases too."""
        with self._lock:
            self._dead = True
            self.refs -= 1
            gone = self.refs == 0
        if gone:
            self._close_unlink()

    def _close_unlink(self) -> None:
        try:
            self.f.close()
        except Exception as e:
            log.debug("segment close failed for %s: %s", self.path, e)
        try:
            os.unlink(self.path)
        except OSError as e:
            log.debug("segment unlink failed for %s: %s", self.path, e)

    def close(self) -> None:
        try:
            self.f.close()
        except Exception as e:
            log.debug("segment close failed for %s: %s", self.path, e)

    # reads -----------------------------------------------------------

    def _block_at(self, i: int, cache) -> list:
        _, off, ln = self.index[i]
        ck = (self.path, off)
        blk = cache.get(ck)
        if blk is None:
            # positioned read: the unlocked compaction build iterates
            # victim segments from a worker thread while lock-holding
            # foreground gets read the same Segment — a shared seek
            # cursor would interleave
            if hasattr(os, "pread"):
                raw = os.pread(self.f.fileno(), ln, off)
            else:
                with self._lock:
                    self.f.seek(off)
                    raw = self.f.read(ln)
            blk = [(bytes(k), None if v is None else bytes(v))
                   for k, v in msgpack.unpackb(raw, raw=True)]
            cache.put(ck, blk)
        return blk

    def _block_index_for(self, key: bytes) -> int:
        """Index of the block that could contain `key` (-1 if before
        the first block)."""
        lo, hi = 0, len(self.index)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.index[mid][0] <= key:
                lo = mid + 1
            else:
                hi = mid
        return lo - 1

    def get(self, key: bytes, cache) -> tuple:
        """(found, value|None-tombstone). Bloom-filtered."""
        if key < self.min_key or key > self.max_key \
                or not self.bloom.might_contain(key):
            return (False, None)
        bi = self._block_index_for(key)
        if bi < 0:
            return (False, None)
        blk = self._block_at(bi, cache)
        lo = bisect.bisect_left(blk, (key,))
        if lo < len(blk) and blk[lo][0] == key:
            return (True, blk[lo][1])
        return (False, None)

    def iter_from(self, start: Optional[bytes], cache,
                  reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        """Yield (key, value|None) from `start` (inclusive forward /
        inclusive-upper reverse) in scan order. The entry position
        inside the first block is bisected, not scanned — a seek is
        O(log block), which is what makes delimiter skip-scan cheap."""
        n = len(self.index)
        if not reverse:
            bi = 0 if start is None else max(0, self._block_index_for(start))
            for i in range(bi, n):
                blk = self._block_at(i, cache)
                j = 0 if start is None or i != bi \
                    else bisect.bisect_left(blk, (start,))
                for e in range(j, len(blk)):
                    yield blk[e]
        else:
            bi = n - 1 if start is None else self._block_index_for(start)
            for i in range(bi, -1, -1):
                blk = self._block_at(i, cache)
                j = len(blk) if start is None or i != bi \
                    else bisect.bisect_left(blk, (start + b"\x00",))
                for e in range(j - 1, -1, -1):
                    yield blk[e]


class _BlockCache:
    """Tiny FIFO-ish cache of decoded data blocks, engine-wide. Locked:
    the compaction build thread and lock-holding foreground reads share
    it."""

    def __init__(self, cap: int = BLOCK_CACHE_BLOCKS):
        self.cap = cap
        self._d: dict = {}
        self._lock = threading.Lock()

    def get(self, k):
        with self._lock:
            return self._d.get(k)

    def put(self, k, v) -> None:
        with self._lock:
            if len(self._d) >= self.cap:
                # drop the oldest insertion (dicts preserve order)
                self._d.pop(next(iter(self._d)))
            self._d[k] = v

    def clear(self) -> None:
        with self._lock:
            self._d.clear()


class _Memtable:
    """Sorted in-memory run: dict + sorted key list. Values of None are
    tombstones (mask older runs)."""

    __slots__ = ("d", "keys", "bytes")

    def __init__(self):
        self.d: dict[bytes, Optional[bytes]] = {}
        self.keys: list[bytes] = []
        self.bytes = 0

    def put(self, key: bytes, value: Optional[bytes]) -> None:
        if key in self.d:
            old = self.d[key]
            self.bytes -= len(old) if old is not None else 0
        else:
            bisect.insort(self.keys, key)
            self.bytes += len(key)
        self.d[key] = value
        self.bytes += len(value) if value is not None else 0

    def get(self, key: bytes) -> tuple:
        if key in self.d:
            return (True, self.d[key])
        return (False, None)

    def iter_from(self, start: Optional[bytes],
                  reverse: bool = False) -> Iterator[Tuple[bytes, bytes]]:
        ks = self.keys
        if not reverse:
            i = 0 if start is None else bisect.bisect_left(ks, start)
            for j in range(i, len(ks)):
                k = ks[j]
                yield k, self.d[k]
        else:
            i = len(ks) if start is None else bisect.bisect_right(ks, start)
            for j in range(i - 1, -1, -1):
                k = ks[j]
                yield k, self.d[k]


class _TreeState:
    __slots__ = ("name", "mem", "frozen", "segments", "count")

    def __init__(self, name: str):
        self.name = name
        self.mem = _Memtable()
        self.frozen: list[_Memtable] = []   # newest first
        self.segments: list[Segment] = []   # newest first
        self.count = 0                      # live keys

    def sources(self):
        """All runs, newest first (merge precedence order)."""
        return [self.mem, *self.frozen, *self.segments]


_ABSENT = object()  # undo sentinel: key was not in the memtable


def _merged_iter(sources, start, reverse, cache):
    """K-way merge over runs in precedence order (sources[0] newest).
    Yields (key, value|None) — the newest record per key, tombstones
    included (callers filter)."""
    live = [s for s in sources
            if (len(s.index) if isinstance(s, Segment) else len(s.d))]
    if len(live) == 1:  # fully-compacted common case: no heap at all
        src = live[0]
        yield from (src.iter_from(start, cache, reverse)
                    if isinstance(src, Segment)
                    else src.iter_from(start, reverse))
        return
    iters = []
    for prio, src in enumerate(live):
        if isinstance(src, Segment):
            it = src.iter_from(start, cache, reverse)
        else:
            it = src.iter_from(start, reverse)
        iters.append((prio, it))
    import heapq

    heap = []
    for prio, it in iters:
        for k, v in it:
            heap.append(((k if not reverse else _RevKey(k)), prio, v, it))
            break
    heapq.heapify(heap)
    last_key = None
    while heap:
        sk, prio, v, it = heapq.heappop(heap)
        k = sk.k if reverse else sk
        if k != last_key:
            last_key = k
            yield k, v
        for nk, nv in it:
            heapq.heappush(
                heap, ((nk if not reverse else _RevKey(nk)), prio, nv, it))
            break


class _RevKey:
    """Inverts byte-key ordering for reverse merge heaps."""

    __slots__ = ("k",)

    def __init__(self, k: bytes):
        self.k = k

    def __lt__(self, other) -> bool:
        return self.k > other.k

    def __eq__(self, other) -> bool:
        return self.k == other.k


class LsmEngine:
    """Engine contract: see db.py `_Engine`. Selected via
    `[metadata] db_engine = "lsm"`."""

    NAME = "lsm"

    def __init__(self, path: str, fsync: bool = False,
                 memtable_max_bytes: int = MEMTABLE_MAX_BYTES):
        self.dir = path
        self.fsync = fsync
        self.memtable_max_bytes = memtable_max_bytes
        os.makedirs(path, exist_ok=True)
        self._trees: dict[str, _TreeState] = {}
        self._next_seg = 1
        self._cache = _BlockCache()
        self._depth = 0
        self._txops: list = []      # ops since begin (for the WAL batch)
        self._undo: list = []       # inverse ops (for rollback)
        self.flushes = 0
        self.compactions = 0
        self._load_manifest()
        self._gc_orphan_segments()
        self._wal_path = os.path.join(path, "wal.log")
        self._replay_wal()
        self._wal = open(self._wal_path, "ab")

    # ---- manifest / recovery ----------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.dir, "MANIFEST")

    def _load_manifest(self) -> None:
        p = self._manifest_path()
        if not os.path.exists(p):
            return
        with open(p, "rb") as f:
            m = msgpack.unpackb(f.read(), raw=True)
        self._next_seg = m[b"next_seg"]
        for name_b, info in m[b"trees"].items():
            name = name_b.decode()
            ts = _TreeState(name)
            ts.count = info[b"count"]
            for seg_id in info[b"segments"]:
                sp = os.path.join(self.dir, f"seg-{seg_id}.sst")
                ts.segments.append(Segment(sp, seg_id))
            self._trees[name] = ts

    def _write_manifest(self) -> None:
        m = {
            "next_seg": self._next_seg,
            "trees": {
                name: {"count": ts.count,
                       "segments": [s.seg_id for s in ts.segments]}
                for name, ts in self._trees.items()
            },
        }
        tmp = self._manifest_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(m, use_bin_type=True))
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path())
        if self.fsync:
            # power-loss safety (fsync=True claims sqlite FULL parity):
            # persist the DIRECTORY entries — the rename above and any
            # seg-*.sst created since the last manifest — before the
            # caller truncates the WAL; without this a power cut can
            # leave a manifest naming a segment whose dirent never hit
            # disk (unopenable db) or revert to the old manifest after
            # the WAL reset (silent loss)
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)

    def _gc_orphan_segments(self) -> None:
        """Segment files written by a flush/compaction that crashed
        before its manifest rename are invisible garbage: delete them
        (their content is still covered by the WAL / old segments)."""
        live = {s.seg_id for ts in self._trees.values()
                for s in ts.segments}
        for fn in os.listdir(self.dir):
            if not (fn.startswith("seg-") and fn.endswith(".sst")):
                continue
            try:
                sid = int(fn[4:-4])
            except ValueError:
                continue
            if sid not in live:
                try:
                    os.unlink(os.path.join(self.dir, fn))
                except OSError:
                    pass

    def _replay_wal(self) -> None:
        if not os.path.exists(self._wal_path):
            return
        with open(self._wal_path, "rb") as f:
            data = f.read()
        pos = 0
        replay_drops: list[Segment] = []
        while pos + _WAL_HDR.size <= len(data):
            ln, crc = _WAL_HDR.unpack_from(data, pos)
            body = data[pos + _WAL_HDR.size: pos + _WAL_HDR.size + ln]
            if len(body) < ln or zlib.crc32(body) != crc:
                break  # torn tail from a crash mid-append: stop here
            pos += _WAL_HDR.size + ln
            for op in msgpack.unpackb(body, raw=True):
                kind = op[0]
                tree = op[1].decode()
                if kind == b"e":
                    self._trees.setdefault(tree, _TreeState(tree))
                    continue
                ts = self._trees.setdefault(tree, _TreeState(tree))
                if kind == b"p":
                    self._apply_put(ts, bytes(op[2]),
                                    None if op[3] is None else bytes(op[3]))
                elif kind == b"c":
                    replay_drops.extend(self._apply_clear(ts))
        if pos < len(data):
            # torn tail: truncate it away, or commits acknowledged after
            # this recovery would be appended BEYOND the garbage and be
            # unreachable to the next replay (silent loss)
            log.warning("lsm %s: truncating torn WAL tail at %d (%d bytes"
                        " discarded)", self.dir, pos, len(data) - pos)
            with open(self._wal_path, "r+b") as f:
                f.truncate(pos)
        if replay_drops:
            # a replayed clear drops segments: persist the new (empty)
            # segment list BEFORE unlinking, so a crash here never
            # leaves the manifest pointing at deleted files
            self._write_manifest()
            for s in replay_drops:
                s.drop()

    # ---- primitive state changes (shared by live path + replay) ------

    def _exists(self, ts: _TreeState, key: bytes) -> bool:
        for src in ts.sources():
            if isinstance(src, Segment):
                found, v = src.get(key, self._cache)
            else:
                found, v = src.get(key)
            if found:
                return v is not None
        return False

    def _apply_put(self, ts: _TreeState, key: bytes,
                   value: Optional[bytes],
                   known_existed: Optional[bool] = None) -> int:
        """Set/tombstone one key; returns the live-count delta.
        `known_existed` skips the source walk when the caller just read
        the key under the same lock (the Tree/Transaction facades
        always do — an UPDATE-heavy workload like the merkle trie would
        otherwise pay a redundant bloom+block lookup per write)."""
        existed = self._exists(ts, key) if known_existed is None \
            else known_existed
        ts.mem.put(key, value)
        delta = (0 if existed else 1) if value is not None \
            else (-1 if existed else 0)
        ts.count += delta
        return delta

    def _apply_clear(self, ts: _TreeState) -> list:
        """Reset a tree's in-memory state; returns the detached
        segments — the CALLER drops them after persisting the manifest
        (unlink-after-manifest ordering)."""
        old_segs = ts.segments
        ts.mem = _Memtable()
        ts.frozen = []
        ts.segments = []
        ts.count = 0
        return old_segs

    # ---- engine contract ---------------------------------------------

    def ensure_tree(self, name: str) -> None:
        if name in self._trees:
            return
        self._trees[name] = _TreeState(name)
        # record outside any tx frame: tree creation survives rollback
        # (sqlite DDL behaves the same under its autocommit CREATE)
        self._wal_append([("e", name)])

    def list_trees(self) -> list[str]:
        return list(self._trees)

    def get(self, tree: str, key: bytes) -> Optional[bytes]:
        ts = self._trees[tree]
        for src in ts.sources():
            if isinstance(src, Segment):
                found, v = src.get(key, self._cache)
            else:
                found, v = src.get(key)
            if found:
                return v
        return None

    def put(self, tree: str, key: bytes, value: bytes,
            prev=PREV_UNKNOWN) -> None:
        ts = self._trees[tree]
        undo_prev = ts.mem.d.get(key, _ABSENT)
        known = None if prev is PREV_UNKNOWN else prev is not None
        delta = self._apply_put(ts, key, value, known_existed=known)
        if self._depth:
            self._txops.append(("p", tree, key, value))
            self._undo.append(("p", ts, key, undo_prev, delta))
        else:  # autocommit (never happens via db.py, which always frames)
            self._wal_append([("p", tree, key, value)])

    def delete(self, tree: str, key: bytes, prev=PREV_UNKNOWN) -> None:
        ts = self._trees[tree]
        undo_prev = ts.mem.d.get(key, _ABSENT)
        known = None if prev is PREV_UNKNOWN else prev is not None
        delta = self._apply_put(ts, key, None, known_existed=known)
        if self._depth:
            self._txops.append(("p", tree, key, None))
            self._undo.append(("p", ts, key, undo_prev, delta))
        else:
            self._wal_append([("p", tree, key, None)])

    def clear(self, tree: str) -> None:
        ts = self._trees[tree]
        old_mem, old_frozen, old_segs, old_count = \
            ts.mem, ts.frozen, ts.segments, ts.count
        ts.mem = _Memtable()
        ts.frozen = []
        ts.segments = []
        ts.count = 0
        if self._depth:
            self._txops.append(("c", tree))
            # defer unlinking to commit — a rollback restores the list
            self._txops.append(("__drop__", old_segs))
            self._undo.append(("c", ts, old_mem, old_frozen, old_segs,
                               old_count))
        else:
            self._wal_append([("c", tree)])
            # manifest first, unlink after: a crash in between leaves
            # orphan files (GC'd on open), never a dangling manifest
            self._write_manifest()
            for s in old_segs:
                s.drop()

    def length(self, tree: str) -> int:
        return self._trees[tree].count

    def range(self, tree: str, start, end, reverse, limit=None) -> list:
        out = []
        # reverse: start descending at `end` (exclusive — the k >= end
        # skip below removes the single boundary hit), stop below start
        scan_start = start if not reverse else end
        it = _merged_iter(self._trees[tree].sources(), scan_start,
                          reverse, self._cache)
        for k, v in it:
            if not reverse:
                if end is not None and k >= end:
                    break
            else:
                if end is not None and k >= end:
                    continue
                if start is not None and k < start:
                    break
            if v is None:
                continue
            out.append((k, v))
            if limit is not None and len(out) >= limit:
                break
        return out

    # transactions -----------------------------------------------------

    def begin(self) -> None:
        self._depth += 1

    def commit(self) -> None:
        self._depth -= 1
        if self._depth:
            return
        ops = [o for o in self._txops if o[0] != "__drop__"]
        drops = [o for o in self._txops if o[0] == "__drop__"]
        self._txops = []
        self._undo = []
        if ops:
            self._wal_append(ops)
        if any(segs for _, segs in drops):
            # a committed clear() detached segments: persist the new
            # segment list BEFORE unlinking (a crash in between leaves
            # orphan files, which open-time GC removes — the reverse
            # order would leave a manifest naming deleted files and an
            # unopenable db)
            self._write_manifest()
        for _, segs in drops:
            for s in segs:
                s.drop()
        self._maybe_flush()

    def rollback(self) -> None:
        self._depth -= 1
        if self._depth:
            return
        for entry in reversed(self._undo):
            if entry[0] == "p":
                _, ts, key, prev, delta = entry
                ts.count -= delta
                if prev is _ABSENT:
                    # remove the key from the memtable again
                    if key in ts.mem.d:
                        old = ts.mem.d.pop(key)
                        ts.mem.bytes -= len(key) + (len(old) if old else 0)
                        i = bisect.bisect_left(ts.mem.keys, key)
                        if i < len(ts.mem.keys) and ts.mem.keys[i] == key:
                            ts.mem.keys.pop(i)
                else:
                    ts.mem.put(key, prev)
            else:  # clear
                _, ts, mem, frozen, segments, count = entry
                ts.mem, ts.frozen, ts.segments, ts.count = \
                    mem, frozen, segments, count
        self._txops = []
        self._undo = []

    def _wal_append(self, ops: list) -> None:
        body = msgpack.packb(ops, use_bin_type=True)
        self._wal.write(_WAL_HDR.pack(len(body), zlib.crc32(body)) + body)
        self._wal.flush()
        if self.fsync:
            os.fsync(self._wal.fileno())

    # ---- flush -------------------------------------------------------

    def _mem_bytes(self) -> int:
        return sum(ts.mem.bytes + sum(m.bytes for m in ts.frozen)
                   for ts in self._trees.values())

    def _maybe_flush(self) -> None:
        if self._mem_bytes() >= self.memtable_max_bytes:
            self.flush()
            # inline backpressure: a process without the maintenance
            # worker (bench, CLI) must not accumulate unbounded runs
            for name, ts in self._trees.items():
                while len(ts.segments) > MAX_SEGMENTS_HARD:
                    if not self._compact_tree(name):
                        break

    def flush(self) -> None:
        """Write every non-empty memtable (active + frozen) as one new
        segment per tree, persist the manifest, then reset the WAL —
        every surviving WAL byte would now be redundant."""
        wrote = False
        for ts in self._trees.values():
            runs = [ts.mem, *ts.frozen]
            if not any(m.d for m in runs):
                continue
            seg = self._write_segment_from_runs(runs)
            ts.segments.insert(0, seg)
            ts.mem = _Memtable()
            ts.frozen = []
            wrote = True
        if not wrote:
            return
        self.flushes += 1
        self._write_manifest()
        self._wal.close()
        self._wal = open(self._wal_path, "wb")
        if self.fsync:
            os.fsync(self._wal.fileno())

    def _write_segment_from_runs(self, runs) -> Segment:
        entries = _merged_iter(runs, None, False, self._cache)
        return self._write_segment(entries)

    def _write_segment(self, entries, seg_id: Optional[int] = None) -> Segment:
        """`entries` yields (key, value|None) ascending; tombstones are
        kept (the caller pre-filters when they may drop). `seg_id` must
        be pre-allocated (under the Db lock) when called from the
        unlocked compaction build — drawing from _next_seg here would
        race a concurrent foreground flush onto the same file."""
        if seg_id is None:
            seg_id = self._next_seg
            self._next_seg += 1
        path = os.path.join(self.dir, f"seg-{seg_id}.sst")
        try:
            return self._write_segment_file(path, seg_id, entries)
        except BaseException:
            # a build that dies mid-write must not leave a partial
            # .sst around (orphan GC only runs at open)
            try:
                os.unlink(path)
            except OSError:
                pass
            raise

    def _write_segment_file(self, path, seg_id, entries) -> Segment:
        index = []
        keys = []
        count = 0
        data_bytes = 0
        min_key = max_key = None
        with open(path, "wb") as f:
            block: list = []
            bsize = 0

            def flush_block():
                nonlocal bsize
                if not block:
                    return
                raw = msgpack.packb(block, use_bin_type=True)
                index.append((block[0][0], f.tell(), len(raw)))
                f.write(raw)
                block.clear()
                bsize = 0

            for k, v in entries:
                if min_key is None:
                    min_key = k
                max_key = k
                keys.append(k)
                if v is not None:
                    count += 1
                    data_bytes += len(k) + len(v)
                block.append((k, v))
                bsize += len(k) + (len(v) if v is not None else 0) + 8
                if bsize >= BLOCK_BYTES:
                    flush_block()
            flush_block()
            bloom = Bloom.build(keys)
            foot = msgpack.packb({
                "index": [[k, o, ln] for k, o, ln in index],
                "bloom": bytes(bloom.bits),
                "nbits": bloom.nbits,
                "count": count,
                "min": min_key or b"",
                "max": max_key or b"",
                "bytes": data_bytes,
            }, use_bin_type=True)
            f.write(foot)
            f.write(struct.pack("<q", len(foot)) + _MAGIC)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        return Segment(path, seg_id)

    # ---- compaction ---------------------------------------------------

    def compaction_backlog(self) -> int:
        """Mergeable-run pressure: segments beyond one fully-compacted
        run per tier, summed over trees. The governor-paced worker
        drains this; /v1/metadata and meta_* gauges report it."""
        total = 0
        for ts in self._trees.values():
            if len(ts.segments) >= TIER_FANIN:
                total += len(ts.segments) - 1
        return total

    def _pick_compaction(self) -> Optional[str]:
        worst, worst_n = None, TIER_FANIN - 1
        for name, ts in self._trees.items():
            if len(ts.segments) > worst_n:
                worst, worst_n = name, len(ts.segments)
        return worst

    def compact_once(self) -> bool:
        """One size-tiered merge step; returns True if work was done."""
        name = self._pick_compaction()
        if name is None:
            return False
        return self._compact_tree(name)

    def compact_full(self) -> None:
        """Merge every tree down to a single run (read-optimized state:
        range scans take the no-heap fast path). Maximum write
        amplification — for bulk-load finalization and benches, not the
        steady-state worker."""
        self.flush()
        for name, ts in self._trees.items():
            while len(ts.segments) > 1:
                self._compact_tree(name)

    def _compact_tree(self, name: str) -> bool:
        plan = self._plan_compaction(name)
        if plan is None:
            return False
        try:
            seg = self._build_compaction(plan)
        except BaseException:
            self._abort_compaction(plan)
            raise
        return self._commit_compaction(plan, seg)

    # The three-phase split exists for the maintenance worker: plan and
    # commit run under the Db lock in O(ms); build — the actual merge,
    # seconds at scale — runs UNLOCKED over the pinned immutable inputs
    # so foreground metadata ops never stall behind a compaction.

    def _plan_compaction(self, name: str) -> Optional[tuple]:
        ts = self._trees[name]
        segs = ts.segments
        if len(segs) < 2:
            return None
        # size-tiered: merge the longest contiguous run (newest..older)
        # of segments whose sizes stay within 4x of the run's smallest;
        # fall back to the oldest TIER_FANIN when nothing tiers up.
        best = None
        for i in range(len(segs) - 1):
            lo = hi = segs[i].data_bytes + 1
            j = i
            while j + 1 < len(segs):
                nxt = segs[j + 1].data_bytes + 1
                lo2, hi2 = min(lo, nxt), max(hi, nxt)
                if hi2 > 4 * lo2:
                    break
                lo, hi = lo2, hi2
                j += 1
            if j - i + 1 >= TIER_FANIN and (best is None
                                            or j - i + 1 > best[1]):
                best = (i, j - i + 1)
        if best is not None:
            run_start, run_len = best
        else:
            run_len = min(TIER_FANIN, len(segs))
            run_start = len(segs) - run_len
        victims = segs[run_start:run_start + run_len]
        includes_oldest = run_start + run_len == len(segs)
        for s in victims:
            s.acquire()  # pin the inputs for the unlocked build
        # allocate the output's id HERE, under the Db lock — the build
        # runs unlocked, and drawing from _next_seg there would race a
        # foreground flush onto the same seg file
        seg_id = self._next_seg
        self._next_seg += 1
        return (name, victims, includes_oldest, seg_id)

    def _build_compaction(self, plan: tuple) -> Segment:
        _, victims, includes_oldest, seg_id = plan
        merged = _merged_iter(victims, None, False, self._cache)
        if includes_oldest:
            # nothing older can resurrect these keys: drop tombstones
            merged = ((k, v) for k, v in merged if v is not None)
        return self._write_segment(merged, seg_id=seg_id)

    def _abort_compaction(self, plan: tuple) -> None:
        for s in plan[1]:
            s.release()

    def _commit_compaction(self, plan: tuple, new_seg: Segment) -> bool:
        name, victims, includes_oldest, _seg_id = plan
        ts = self._trees.get(name)
        if ts is None or any(v not in ts.segments for v in victims):
            # a clear() raced the build: the merge output is stale
            new_seg.drop()
            self._abort_compaction(plan)
            return False
        run_start = ts.segments.index(victims[0])
        if includes_oldest and new_seg.count == 0:
            # everything merged away (pure-tombstone runs): keep nothing
            replacement: list[Segment] = []
            new_seg.drop()
        else:
            replacement = [new_seg]
        ts.segments = ts.segments[:run_start] + replacement \
            + ts.segments[run_start + len(victims):]
        self._write_manifest()
        for s in victims:
            s.release()  # the plan's pin
            s.drop()     # the manifest's ref
        self.compactions += 1
        return True

    # ---- snapshots ----------------------------------------------------

    def iter_snapshot(self, tree: str, start: Optional[bytes] = None,
                      end: Optional[bytes] = None) -> "SnapshotIterator":
        """A stable, streaming view of the tree as of now: freezes the
        active memtable (pointer swap) and refs the current segments.
        Flushes/compactions proceed underneath; the caller must close()
        (or exhaust) the iterator to release the segment refs."""
        ts = self._trees[tree]
        if ts.mem.d:
            ts.frozen.insert(0, ts.mem)
            ts.mem = _Memtable()
        sources = [*ts.frozen, *[s.acquire() for s in ts.segments]]
        return SnapshotIterator(sources, start, end, self._cache)

    def snapshot(self, to_dir: str) -> None:
        """Hot copy: flush, then link/copy manifest + segments. The
        result opens as a standalone lsm db."""
        import shutil

        self.flush()
        os.makedirs(to_dir, exist_ok=True)
        dest = os.path.join(to_dir, os.path.basename(self.dir.rstrip("/")))
        os.makedirs(dest, exist_ok=True)
        self._write_manifest()
        shutil.copy2(self._manifest_path(), os.path.join(dest, "MANIFEST"))
        for ts in self._trees.values():
            for s in ts.segments:
                tgt = os.path.join(dest, os.path.basename(s.path))
                if not os.path.exists(tgt):
                    try:
                        os.link(s.path, tgt)
                    except OSError:
                        shutil.copy2(s.path, tgt)

    # ---- stats / lifecycle -------------------------------------------

    def stats(self) -> dict:
        return {
            "engine": self.NAME,
            "trees": len(self._trees),
            "segments": sum(len(ts.segments)
                            for ts in self._trees.values()),
            "compaction_backlog": self.compaction_backlog(),
            "wal_bytes": self._wal_size(),
            "memtable_bytes": self._mem_bytes(),
            "flushes": self.flushes,
            "compactions": self.compactions,
            "rows": sum(ts.count for ts in self._trees.values()),
        }

    def _wal_size(self) -> int:
        try:
            return os.path.getsize(self._wal_path)
        except OSError:
            return 0

    def close(self) -> None:
        self.flush()
        self._wal.close()
        for ts in self._trees.values():
            for s in ts.segments:
                s.close()


class SnapshotIterator:
    """Streaming merged view over frozen runs; releases segment refs on
    close/exhaustion. Iterates (key, value) with tombstones filtered."""

    def __init__(self, sources, start, end, cache):
        self._segments = [s for s in sources if isinstance(s, Segment)]
        self._it = _merged_iter(sources, start, False, cache)
        self._end = end
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        for k, v in self._it:
            if self._end is not None and k >= self._end:
                break
            if v is None:
                continue
            return k, v
        self.close()
        raise StopIteration

    def close(self) -> None:
        if not self._done:
            self._done = True
            for s in self._segments:
                s.release()

    def __del__(self):
        self.close()


class LsmMaintenanceWorker:
    """Background size-tiered compaction, governor-paced.

    Worker-protocol duck type (utils/background.Worker): one
    compact_once() step per tick in a thread (the merge is pure disk +
    CPU and must not block the event loop); `tranquility` seconds of
    sleep between steps is OWNED by the qos governor, exactly like the
    table syncers' pacing — compaction yields to foreground latency and
    sprints on an idle node."""

    def __init__(self, db):
        self.db = db
        self.name = "lsm compaction"
        self.tranquility = 0.0
        self.steps = 0

    def _engine(self) -> Optional[LsmEngine]:
        e = getattr(self.db, "_engine", None)
        return e if isinstance(e, LsmEngine) else None

    async def work(self):
        import asyncio

        from ..utils.background import WState

        e = self._engine()
        if e is None:
            return WState.DONE
        if self.tranquility > 0:
            await asyncio.sleep(self.tranquility)

        # plan (locked, ms) -> build (UNLOCKED: the merge reads only
        # pinned immutable segments) -> commit (locked, ms) — a
        # multi-second merge never stalls foreground metadata ops
        def plan():
            with self.db._lock:
                name = e._pick_compaction()
                return e._plan_compaction(name) if name else None

        p = await asyncio.to_thread(plan)
        if p is None:
            return WState.IDLE
        try:
            seg = await asyncio.to_thread(e._build_compaction, p)
        except BaseException:
            with self.db._lock:
                e._abort_compaction(p)
            raise

        def commit() -> bool:
            with self.db._lock:
                return e._commit_compaction(p, seg)

        did = await asyncio.to_thread(commit)
        if did:
            self.steps += 1
            from ..utils.metrics import registry

            registry().inc("meta_compaction_steps")
            return WState.BUSY
        return WState.IDLE

    async def wait_for_work(self):
        import asyncio

        await asyncio.sleep(1.0)

    def info(self):
        from ..utils.background import WorkerInfo

        e = self._engine()
        backlog = e.compaction_backlog() if e is not None else 0
        return WorkerInfo(name=self.name, queue_length=backlog,
                          progress=f"{self.steps} merges")
