"""Embedded KV abstraction: named trees + cross-tree transactions.

Ref parity: src/db/lib.rs (Db/Tree/Transaction facade, on_commit hooks,
snapshot), src/db/sqlite_adapter.rs, src/db/open.rs. LMDB is not
available in this image, so the engines are sqlite (durable default),
memory (tests/sim) and lsm (log-structured merge engine for metadata
at millions of keys — see lsm.py and README "Metadata at scale"). The
same test-suite runs against all engines, mirroring src/db/test.rs.
"""

from .db import Db, Tree, Transaction, TxAbort, blocking_api, open_db

__all__ = ["Db", "Tree", "Transaction", "TxAbort", "blocking_api",
           "open_db"]
