"""KV facade + sqlite/memory engines.

Ref parity: src/db/lib.rs:28-432 (Db, Tree, Transaction, TxResult, on_commit),
src/db/sqlite_adapter.rs, src/db/open.rs:65-125 (engine selection).

Concurrency model: engine calls are synchronous and guarded by an RLock; the
asyncio server calls them directly (ops are sub-millisecond) or via
asyncio.to_thread for bulk scans. Transactions are serializable: one writer at
a time (the RLock), like the reference's LMDB single-writer model.
"""

from __future__ import annotations

import bisect
import os
import shutil
import sqlite3
import threading
from typing import Callable, Iterator, Optional, Tuple


# "previous value unknown" sentinel for _Engine.put/delete's `prev`
# hint: the Tree/Transaction facades have ALWAYS just read the old
# value when they write, and an engine that needs existence for live-
# count bookkeeping (lsm) can skip a full source walk when it is told.
PREV_UNKNOWN = object()


class TxAbort(Exception):
    """Raise inside a transaction body to roll back. ref: db/lib.rs TxError::Abort."""

    def __init__(self, value=None):
        self.value = value


def blocking_api(fn):
    """Marker for synchronous, potentially-blocking API functions.

    garage-lint's GL10 reads this (the decorator, or a `blocking_api =
    True` class attribute) instead of guessing from receiver names: a
    non-awaited call that resolves to a marked function is a blocking
    atom when reached from an async frame without a to_thread hop.
    Runtime no-op beyond the attribute (ISSUE 14 satellite)."""
    fn.__blocking_api__ = True
    return fn


class Db:
    # every public method runs engine code under the Db lock — sqlite
    # or LSM I/O that must never run directly on the event loop
    blocking_api = True

    def __init__(self, engine: "_Engine"):
        self._engine = engine
        self._lock = threading.RLock()
        self._trees: dict[str, Tree] = {}

    @property
    def engine_name(self) -> str:
        return self._engine.NAME

    def open_tree(self, name: str) -> "Tree":
        with self._lock:
            t = self._trees.get(name)
            if t is None:
                self._engine.ensure_tree(name)
                t = Tree(self, name)
                self._trees[name] = t
            return t

    def list_trees(self) -> list[str]:
        return self._engine.list_trees()

    def transaction(self, body: Callable[["Transaction"], object]):
        """Run `body(tx)`; commit on return, roll back on TxAbort/exception.
        Returns body's return value; TxAbort re-raises after rollback.
        on_commit hooks registered via tx.on_commit run after a successful
        commit (ref: db/lib.rs:322)."""
        with self._lock:
            tx = Transaction(self._engine)
            self._engine.begin()
            try:
                result = body(tx)
            except BaseException:
                self._engine.rollback()
                raise
            self._engine.commit()
            for hook in tx._hooks:
                hook()
            return result

    def snapshot(self, to_dir: str) -> None:
        """Engine-level hot copy. ref: db/lib.rs snapshot, model/snapshot.rs."""
        with self._lock:
            self._engine.snapshot(to_dir)

    def engine_stats(self) -> dict:
        """Per-engine internals for operators (admin GET /v1/metadata +
        the meta_* gauges): segment counts, WAL size, compaction
        backlog for lsm; file size for sqlite; row totals everywhere."""
        with self._lock:
            return self._engine.stats()

    def close(self) -> None:
        with self._lock:
            self._engine.close()


class Tree:
    """A named keyspace with ordered byte keys. ref: db/lib.rs:98-270."""

    # sqlite/LSM I/O under the Db lock: blocking by contract (GL10)
    blocking_api = True

    def __init__(self, db: Db, name: str):
        self._db = db
        self._e = db._engine
        self.name = name

    def get(self, key: bytes) -> Optional[bytes]:
        with self._db._lock:
            return self._e.get(self.name, key)

    def insert(self, key: bytes, value: bytes) -> Optional[bytes]:
        """Returns previous value (the reference returns the old value)."""
        with self._db._lock:
            self._e.begin()
            try:
                old = self._e.get(self.name, key)
                self._e.put(self.name, key, value, prev=old)
            except BaseException:
                self._e.rollback()
                raise
            self._e.commit()
            return old

    def remove(self, key: bytes) -> Optional[bytes]:
        with self._db._lock:
            self._e.begin()
            try:
                old = self._e.get(self.name, key)
                if old is not None:
                    self._e.delete(self.name, key, prev=old)
            except BaseException:
                self._e.rollback()
                raise
            self._e.commit()
            return old

    def clear(self) -> None:
        with self._db._lock:
            self._e.begin()
            self._e.clear(self.name)
            self._e.commit()

    def __len__(self) -> int:
        with self._db._lock:
            return self._e.length(self.name)

    def first(self) -> Optional[Tuple[bytes, bytes]]:
        for kv in self.iter():
            return kv
        return None

    def get_gt(self, key: bytes) -> Optional[Tuple[bytes, bytes]]:
        for kv in self.iter(start=key + b"\x00", limit=1):
            return kv
        return None

    def iter(self, start: Optional[bytes] = None, end: Optional[bytes] = None,
             reverse: bool = False,
             limit: Optional[int] = None) -> Iterator[Tuple[bytes, bytes]]:
        """Ordered scan over [start, end). Materialized per-call to stay
        consistent under concurrent writes; pass `limit` for cursor-style
        batch walks so a batch never materializes the whole tail."""
        with self._db._lock:
            items = self._e.range(self.name, start, end, reverse, limit)
        return iter(items)


class Transaction:
    """Operations inside Db.transaction(); sees its own writes.
    ref: db/lib.rs:272-384 (ITx)."""

    # runs inside Db.transaction's engine critical section (GL10)
    blocking_api = True

    def __init__(self, engine: "_Engine"):
        self._e = engine
        self._hooks: list[Callable[[], None]] = []

    def get(self, tree: Tree, key: bytes) -> Optional[bytes]:
        return self._e.get(tree.name, key)

    def insert(self, tree: Tree, key: bytes, value: bytes) -> Optional[bytes]:
        old = self._e.get(tree.name, key)
        self._e.put(tree.name, key, value, prev=old)
        return old

    def remove(self, tree: Tree, key: bytes) -> Optional[bytes]:
        old = self._e.get(tree.name, key)
        if old is not None:
            self._e.delete(tree.name, key, prev=old)
        return old

    def length(self, tree: Tree) -> int:
        return self._e.length(tree.name)

    def range(self, tree: Tree, start: Optional[bytes] = None,
              end: Optional[bytes] = None, reverse: bool = False,
              limit: Optional[int] = None):
        return self._e.range(tree.name, start, end, reverse, limit)

    def on_commit(self, hook: Callable[[], None]) -> None:
        self._hooks.append(hook)


# ---------------------------------------------------------------- engines


class _Engine:
    NAME = "?"

    def ensure_tree(self, name: str) -> None: ...
    def list_trees(self) -> list[str]: ...
    def get(self, tree: str, key: bytes) -> Optional[bytes]: ...

    # `prev` is a hint: the stored value (None = absent) the caller just
    # read under the same lock, or PREV_UNKNOWN
    def put(self, tree: str, key: bytes, value: bytes,
            prev=PREV_UNKNOWN) -> None: ...
    def delete(self, tree: str, key: bytes, prev=PREV_UNKNOWN) -> None: ...
    def clear(self, tree: str) -> None: ...
    def length(self, tree: str) -> int: ...
    def range(self, tree, start, end, reverse, limit=None) -> list: ...
    def begin(self) -> None: ...
    def commit(self) -> None: ...
    def rollback(self) -> None: ...
    def snapshot(self, to_dir: str) -> None: ...
    def close(self) -> None: ...

    def stats(self) -> dict:
        return {"engine": self.NAME}


class MemEngine(_Engine):
    """Sorted in-memory store for tests and the deterministic sim harness."""

    NAME = "memory"

    def __init__(self):
        # tree -> (dict, sorted key list)
        self._data: dict[str, dict[bytes, bytes]] = {}
        self._keys: dict[str, list[bytes]] = {}
        self._undo: list | None = None
        self._depth = 0

    def ensure_tree(self, name):
        if name not in self._data:
            self._data[name] = {}
            self._keys[name] = []

    def list_trees(self):
        return list(self._data)

    def get(self, tree, key):
        return self._data[tree].get(key)

    def put(self, tree, key, value, prev=PREV_UNKNOWN):
        d = self._data[tree]
        if self._undo is not None:
            self._undo.append((tree, key, d.get(key)))
        if key not in d:
            bisect.insort(self._keys[tree], key)
        d[key] = value

    def delete(self, tree, key, prev=PREV_UNKNOWN):
        d = self._data[tree]
        if key in d:
            if self._undo is not None:
                self._undo.append((tree, key, d[key]))
            del d[key]
            ks = self._keys[tree]
            i = bisect.bisect_left(ks, key)
            if i < len(ks) and ks[i] == key:
                ks.pop(i)

    def clear(self, tree):
        if self._undo is not None:
            for k, v in self._data[tree].items():
                self._undo.append((tree, k, v))
        self._data[tree] = {}
        self._keys[tree] = []

    def length(self, tree):
        return len(self._data[tree])

    def range(self, tree, start, end, reverse, limit=None):
        ks = self._keys[tree]
        lo = bisect.bisect_left(ks, start) if start is not None else 0
        hi = bisect.bisect_left(ks, end) if end is not None else len(ks)
        sel = ks[lo:hi]
        if reverse:
            sel = list(reversed(sel))
        if limit is not None:
            sel = sel[:limit]
        d = self._data[tree]
        return [(k, d[k]) for k in sel]

    def begin(self):
        self._depth += 1
        if self._depth == 1:
            self._undo = []

    def commit(self):
        self._depth -= 1
        if self._depth == 0:
            self._undo = None

    def rollback(self):
        self._depth -= 1
        if self._depth == 0 and self._undo is not None:
            for tree, key, old in reversed(self._undo):
                if old is None:
                    self._no_undo_delete(tree, key)
                else:
                    self._no_undo_put(tree, key, old)
            self._undo = None

    def _no_undo_put(self, tree, key, value):
        d = self._data[tree]
        if key not in d:
            bisect.insort(self._keys[tree], key)
        d[key] = value

    def _no_undo_delete(self, tree, key):
        d = self._data[tree]
        if key in d:
            del d[key]
            ks = self._keys[tree]
            i = bisect.bisect_left(ks, key)
            if i < len(ks) and ks[i] == key:
                ks.pop(i)

    def stats(self):
        return {"engine": self.NAME, "trees": len(self._data),
                "rows": sum(len(d) for d in self._data.values())}

    def snapshot(self, to_dir):
        # dev/test engine: dump all trees as one msgpack file so the
        # snapshot workers + CLI behave uniformly across engines
        import msgpack
        import os

        os.makedirs(to_dir, exist_ok=True)
        payload = {t: list(self._data[t].items()) for t in self._data}
        tmp = os.path.join(to_dir, "memdb.msgpack.tmp")
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(payload, use_bin_type=True))
        os.replace(tmp, os.path.join(to_dir, "memdb.msgpack"))

    def close(self):
        pass


class SqliteEngine(_Engine):
    """sqlite3-backed engine; one SQL table per tree.
    ref: src/db/sqlite_adapter.rs."""

    NAME = "sqlite"

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False,
                                     isolation_level=None)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            "PRAGMA synchronous=%s" % ("FULL" if fsync else "OFF"))
        self._depth = 0
        self._stats_cache: Optional[dict] = None
        self._stats_at = 0.0

    @staticmethod
    def _tbl(name: str) -> str:
        return '"tree_%s"' % name.replace('"', '""')

    def ensure_tree(self, name):
        self._conn.execute(
            f"CREATE TABLE IF NOT EXISTS {self._tbl(name)} "
            "(k BLOB PRIMARY KEY, v BLOB NOT NULL) WITHOUT ROWID")

    def list_trees(self):
        rows = self._conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table' "
            "AND name LIKE 'tree_%'").fetchall()
        return [r[0][5:] for r in rows]

    def get(self, tree, key):
        row = self._conn.execute(
            f"SELECT v FROM {self._tbl(tree)} WHERE k=?", (key,)).fetchone()
        return row[0] if row else None

    def put(self, tree, key, value, prev=PREV_UNKNOWN):
        self._conn.execute(
            f"INSERT INTO {self._tbl(tree)}(k,v) VALUES(?,?) "
            "ON CONFLICT(k) DO UPDATE SET v=excluded.v", (key, value))

    def delete(self, tree, key, prev=PREV_UNKNOWN):
        self._conn.execute(f"DELETE FROM {self._tbl(tree)} WHERE k=?", (key,))

    def clear(self, tree):
        self._conn.execute(f"DELETE FROM {self._tbl(tree)}")

    def length(self, tree):
        return self._conn.execute(
            f"SELECT COUNT(*) FROM {self._tbl(tree)}").fetchone()[0]

    def range(self, tree, start, end, reverse, limit=None):
        q = f"SELECT k, v FROM {self._tbl(tree)}"
        conds, params = [], []
        if start is not None:
            conds.append("k >= ?")
            params.append(start)
        if end is not None:
            conds.append("k < ?")
            params.append(end)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        q += " ORDER BY k" + (" DESC" if reverse else "")
        if limit is not None:
            q += " LIMIT ?"
            params.append(limit)
        return self._conn.execute(q, params).fetchall()

    def begin(self):
        self._depth += 1
        if self._depth == 1:
            self._conn.execute("BEGIN IMMEDIATE")

    def commit(self):
        self._depth -= 1
        if self._depth == 0:
            self._conn.execute("COMMIT")

    def rollback(self):
        self._depth -= 1
        if self._depth == 0:
            self._conn.execute("ROLLBACK")

    def snapshot(self, to_dir):
        os.makedirs(to_dir, exist_ok=True)
        dest = os.path.join(to_dir, os.path.basename(self.path))
        dst = sqlite3.connect(dest)
        try:
            self._conn.backup(dst)
        finally:
            dst.close()

    def stats(self):
        # the row total is a COUNT(*) scan per tree — O(all rows) while
        # holding the Db lock. /metrics scrapes every few seconds, so
        # cache it; file size stays live (stat() is cheap)
        import time

        now = time.monotonic()
        if self._stats_cache is None or now - self._stats_at >= 10.0:
            trees = self.list_trees()
            self._stats_cache = {
                "engine": self.NAME, "trees": len(trees),
                "rows": sum(self.length(t) for t in trees)}
            self._stats_at = now
        st = dict(self._stats_cache)
        try:
            st["file_bytes"] = os.path.getsize(self.path)
        except OSError:
            st["file_bytes"] = 0
        return st

    def close(self):
        self._conn.close()


@blocking_api
def open_db(path: str, engine: str = "sqlite", fsync: bool = False) -> Db:
    """ref: src/db/open.rs:65-125 (engine selection; `[metadata]
    db_engine = sqlite|memory|lsm`)."""
    if engine == "sqlite":
        return Db(SqliteEngine(os.path.join(path, "db.sqlite")
                               if not path.endswith(".sqlite") else path,
                               fsync=fsync))
    if engine == "memory":
        return Db(MemEngine())
    if engine == "lsm":
        from .lsm import LsmEngine

        return Db(LsmEngine(os.path.join(path, "db.lsm")
                            if not path.endswith(".lsm") else path,
                            fsync=fsync))
    raise ValueError(f"unknown db engine {engine!r} (sqlite|memory|lsm)")
