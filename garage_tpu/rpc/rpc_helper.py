"""RpcHelper: quorum call orchestration.

Ref parity: src/rpc/rpc_helper.rs:160-766. The transport-agnostic quorum
engine:

- `call`: one node, with timeout + metrics.
- `try_call_many`: N nodes, return at `quorum` successes. Adaptive send:
  issue only `quorum` requests first (preferring self/same-zone/low-ping
  nodes), adding replacements as errors come in; or all at once.
- `try_write_many_sets`: write to multiple quorum sets during layout
  transitions; succeeds when EVERY set reaches its write quorum;
  remaining requests continue in the background.
- `QuorumSetResultTracker`: the bookkeeping shared by both.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..net.message import PRIO_NORMAL
from ..utils.error import QuorumError, RpcError
from .system import System


def _consume_task_result(t: asyncio.Task) -> None:
    if t.cancelled():
        return
    e = t.exception()
    if e is not None:
        logging.getLogger(__name__).debug("straggler rpc failed: %s", e)

log = logging.getLogger("garage_tpu.rpc.helper")

DEFAULT_TIMEOUT = 30.0


@dataclass
class RequestStrategy:
    """ref: rpc_helper.rs RequestStrategy."""

    quorum: int = 1
    prio: int = PRIO_NORMAL
    timeout: float = DEFAULT_TIMEOUT
    send_all_at_once: bool = False
    interrupt_stragglers: bool = True  # reads cancel; writes let them finish


class QuorumSetResultTracker:
    """Per-set success/failure accounting over possibly-overlapping quorum
    sets (ref: rpc_helper.rs:665-766)."""

    def __init__(self, sets: list[list[bytes]], quorum: int):
        self.sets = sets
        self.quorum = quorum
        self.nodes: list[bytes] = []
        seen = set()
        for s in sets:
            for n in s:
                if n not in seen:
                    seen.add(n)
                    self.nodes.append(n)
        self.successes: dict[bytes, Any] = {}
        self.failures: dict[bytes, Exception] = {}

    def success(self, node: bytes, resp) -> None:
        self.successes[node] = resp

    def failure(self, node: bytes, err: Exception) -> None:
        self.failures[node] = err

    def set_counts(self) -> list[tuple[int, int]]:
        """(successes, failures) per set."""
        return [
            (
                sum(1 for n in s if n in self.successes),
                sum(1 for n in s if n in self.failures),
            )
            for s in self.sets
        ]

    def all_quorums_ok(self) -> bool:
        return all(ok >= self.quorum for ok, _ in self.set_counts())

    def too_many_failures(self) -> bool:
        return any(
            fail > len(s) - self.quorum
            for s, (_, fail) in zip(self.sets, self.set_counts())
        )

    def quorum_error(self) -> QuorumError:
        return QuorumError(
            quorum=self.quorum,
            sets=len(self.sets),
            ok=len(self.successes),
            total=len(self.nodes),
            errors=[str(e) for e in self.failures.values()],
        )


class RpcHelper:
    def __init__(self, system: System):
        self.system = system
        self.netapp = system.netapp

    # ---- node ordering (ref: rpc_helper.rs:621-660) --------------------

    def request_order(self, nodes: list[bytes]) -> list[bytes]:
        """self first, then same-zone, then by ping."""
        my_zone = None
        role = self.system.layout_helper.current().node_role(self.netapp.id)
        if role is not None:
            my_zone = role.zone

        def key(n: bytes):
            if n == self.netapp.id:
                return (0, 0.0)
            role = self.system.layout_helper.current().node_role(n)
            same_zone = role is not None and my_zone is not None and role.zone == my_zone
            ping = self.system.peering.ping_avg(n)
            connected = self.system.is_up(n)
            return (
                1 if (same_zone and connected) else (2 if connected else 3),
                ping if ping is not None else 1.0,
            )

        return sorted(nodes, key=key)

    # ---- single call ---------------------------------------------------

    async def call(
        self,
        endpoint,
        node: bytes,
        payload,
        prio: int = PRIO_NORMAL,
        timeout: float = DEFAULT_TIMEOUT,
        stream=None,
    ):
        resp, rstream = await endpoint.call(
            node, payload, prio, stream=stream, timeout=timeout
        )
        return (resp, rstream) if rstream is not None else resp

    # ---- try_call_many (ref: rpc_helper.rs:290-411) --------------------

    async def try_call_many(
        self,
        endpoint,
        nodes: list[bytes],
        payload,
        strategy: RequestStrategy,
        make_payload: Optional[Callable[[bytes], Any]] = None,
    ) -> list:
        """Returns >= quorum successful responses or raises QuorumError."""
        quorum = strategy.quorum
        if quorum > len(nodes):
            raise QuorumError(quorum, 1, 0, len(nodes), ["not enough nodes"])
        order = self.request_order(list(nodes))
        successes: list = []
        errors: list[Exception] = []
        pending: dict[asyncio.Task, bytes] = {}
        next_i = 0

        def launch_one():
            nonlocal next_i
            node = order[next_i]
            next_i += 1
            pl = make_payload(node) if make_payload else payload
            t = asyncio.create_task(
                endpoint.call(node, pl, strategy.prio, timeout=strategy.timeout)
            )
            pending[t] = node

        n_initial = len(order) if strategy.send_all_at_once else min(quorum, len(order))
        for _ in range(n_initial):
            launch_one()
        try:
            while len(successes) < quorum:
                if not pending:
                    raise QuorumError(
                        quorum, 1, len(successes), len(nodes), [str(e) for e in errors]
                    )
                done, _ = await asyncio.wait(
                    pending.keys(), return_when=asyncio.FIRST_COMPLETED
                )
                for t in done:
                    node = pending.pop(t)
                    try:
                        resp, _stream = t.result()
                        successes.append((node, resp))
                    except Exception as e:
                        errors.append(e)
                        if next_i < len(order):
                            launch_one()
            return [r for _, r in successes]
        finally:
            for t in pending:
                if strategy.interrupt_stragglers:
                    t.cancel()
                else:
                    # left running so replicas converge; swallow the result
                    # so a late failure doesn't log "never retrieved"
                    t.add_done_callback(_consume_task_result)

    # ---- try_write_many_sets (ref: rpc_helper.rs:413-538) --------------

    async def try_write_many_sets(
        self,
        endpoint,
        write_sets: list[list],
        payload,
        strategy: RequestStrategy,
        make_payload: Optional[Callable[[Any], Any]] = None,
        make_stream: Optional[Callable[[Any], Any]] = None,
        make_call: Optional[Callable[[Any], Any]] = None,
    ) -> QuorumSetResultTracker:
        """Write to every set with per-set quorum; left-over requests keep
        running in the background after success (so all replicas converge
        without blocking the caller).

        Set entries are opaque quorum keys — normally node ids, but e.g.
        the erasure block path uses (node, shard_index) tuples with a
        `make_call` that issues the per-key RPC itself."""
        tracker = QuorumSetResultTracker(write_sets, strategy.quorum)
        if not tracker.nodes:
            # empty/unassigned layout: fail fast instead of hanging on a
            # future no task will ever resolve
            raise tracker.quorum_error()
        result = asyncio.get_event_loop().create_future()

        async def one(key):
            try:
                if make_call is not None:
                    resp, _ = await make_call(key)
                else:
                    pl = make_payload(key) if make_payload else payload
                    st = make_stream(key) if make_stream else None
                    resp, _ = await endpoint.call(
                        key, pl, strategy.prio, stream=st,
                        timeout=strategy.timeout
                    )
                tracker.success(key, resp)
            except Exception as e:
                tracker.failure(key, e)
            if not result.done():
                if tracker.all_quorums_ok():
                    result.set_result(True)
                elif tracker.too_many_failures():
                    result.set_exception(tracker.quorum_error())

        tasks = [asyncio.create_task(one(n)) for n in tracker.nodes]
        try:
            await result
            return tracker
        except BaseException:
            for t in tasks:
                t.cancel()
            raise
        # on success, remaining tasks continue in background by design
