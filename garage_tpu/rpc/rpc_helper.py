"""RpcHelper: quorum call orchestration, self-healing.

Ref parity: src/rpc/rpc_helper.rs:160-766. The transport-agnostic quorum
engine:

- `call`: one node, with timeout + metrics.
- `try_call_many`: N nodes, return at `quorum` successes. Adaptive send:
  issue only `quorum` requests first (preferring self/same-zone/low-ping
  nodes), adding replacements as errors come in; or all at once.
- `try_write_many_sets`: write to multiple quorum sets during layout
  transitions; succeeds when EVERY set reaches its write quorum;
  remaining requests continue in the background. Idempotent writes may
  opt into HEDGED backup pushes (strategy.hedge=True): a quorum-key
  still unanswered past its holder's observed p95 gets the same call
  re-issued, and the first landing wins — GL02 keeps every such
  opt-in justified (content-addressed shard puts qualify, CRDT
  inserts do not).
- `QuorumSetResultTracker`: the bookkeeping shared by both.
- `HedgedRace`: the shared hedged-wait loop. The hedge logic used to
  exist in three near-copies (here, block `_get_replicate`, erasure
  `_gather_parts`) and the shard-write hedge would have made four;
  the budget, rate-cap token draw, win accounting and loser cleanup
  now live in this one class, and callers keep only their success
  predicate and replacement policy.

Beyond the reference, every call feeds the shared per-peer health
tracker (net/peering.py PeerHealthTracker) and reads it back:

- **Adaptive timeouts**: a peer with enough samples gets
  clamp(p99 * 4) instead of the flat default (the flat value stays the
  ceiling, and the default when no samples exist).
- **Circuit breakers**: request_order ranks peers whose breaker is
  open/exhausted behind healthy ones, so a known-broken peer stops
  being everyone's first choice; half-open peers get a bounded probe
  budget to prove recovery.
- **Hedged reads** (Dean & Barroso, CACM 2013): with
  send_all_at_once=False, if no in-flight request completes within the
  peers' observed p95, a backup request is launched at the next-ranked
  node instead of waiting out an error or timeout. First success wins,
  losers are cancelled, and a global token bucket caps the hedge rate.
- **Named errors**: every transport failure is wrapped so the surfaced
  message carries the peer id and endpoint (`QuorumError.errors`
  entries included) — a bare `TimeoutError` gives operators nothing.
- **Zone-aware quorums** (ISSUE 16, garage_tpu/zones/): request_order
  already prefers same-zone peers, so reads are local-zone-first and
  hedges naturally spill cross-zone; on top of that, nodes sitting in
  a zone `ZoneHealth` reports PARTITIONED sort dead last even while
  their conn state flaps through reconnect churn. Writes pre-verify
  that every quorum set actually spans the layout's `zone_redundancy`
  zones and raise the typed `ZoneSpanError` when placement can't — a
  mis-spread set would otherwise "succeed" W=2 inside one failure
  domain. A per-request `ConsistencyMode.DEGRADED` override on
  `RequestStrategy` lets a caller serve a read from whatever zones
  survive a partition (effective quorum 1, Dynamo-style sloppy read)
  without flipping the whole cluster out of consistent mode.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..net.message import PRIO_NORMAL
from ..utils.error import QuorumError, RpcError, ZoneSpanError
from ..utils.metrics import registry
from .replication_mode import ConsistencyMode
from .system import System


def _consume_task_result(t: asyncio.Task) -> None:
    if t.cancelled():
        return
    e = t.exception()
    if e is not None:
        logging.getLogger(__name__).debug("straggler rpc failed: %s", e)

log = logging.getLogger("garage_tpu.rpc.helper")

DEFAULT_TIMEOUT = 30.0
# at most this many hedges augment one try_call_many call (the global
# token bucket in PeerHealthTracker caps the cluster-wide rate on top)
MAX_HEDGES_PER_CALL = 2


class HedgedRace:
    """One hedged fan-out (Dean & Barroso hedging, shared engine).

    Owns the pending-task map, the hedge-delay FIRST_COMPLETED wait,
    the per-call hedge budget, the global rate-cap token draw, the
    launch/win metrics and loser cleanup. Callers supply a launch
    callback (what a hedge actually issues: next-ranked node for reads,
    a re-issued call for idempotent writes) and decide success from the
    completed tasks themselves.

    Works with health=None (bare test stubs): hedging simply stays off
    and wait() degrades to a plain FIRST_COMPLETED."""

    def __init__(self, health, label: str, *,
                 enabled: Optional[bool] = None,
                 max_hedges: int = MAX_HEDGES_PER_CALL):
        self.health = health
        self.label = label
        self.hedging = health is not None and bool(
            enabled if enabled is not None else health.hedging_enabled)
        self.max_hedges = max_hedges
        self.hedges = 0
        self.pending: dict[asyncio.Task, tuple[Any, bool]] = {}

    def launch(self, key, coro, hedged: bool = False) -> asyncio.Task:
        t = asyncio.create_task(coro)
        self.pending[t] = (key, hedged)
        return t

    def take_hedge(self) -> bool:
        """Draw one hedge: per-call budget, then the cluster-wide token
        bucket. A refused token disables hedging for the rest of this
        race (plain waits from here on) — exactly the old inline
        behavior."""
        if not self.hedging or self.hedges >= self.max_hedges:
            return False
        if not self.health.try_take_hedge():
            self.hedging = False
            return False
        self.hedges += 1
        registry().inc("rpc_hedge_launched", endpoint=self.label)
        return True

    async def wait(self, can_hedge: bool, launch_hedge=None,
                   hedge_nodes=None) -> list:
        """One FIRST_COMPLETED round over the pending tasks.

        If nothing lands within the peers' observed-p95 hedge delay and
        a hedge is allowed, launch_hedge() is invoked (after the token
        draw) and [] is returned for this round. Otherwise the
        completed tasks are popped and returned as (key, hedged, task)
        triples — the caller inspects results and reports wins via
        note_success()."""
        can = (self.hedging and can_hedge and launch_hedge is not None
               and self.hedges < self.max_hedges)
        if can:
            nodes = (hedge_nodes if hedge_nodes is not None
                     else [k for k, _ in self.pending.values()])
            timeout = self.health.hedge_delay(nodes)
        else:
            timeout = None
        done, _ = await asyncio.wait(
            self.pending.keys(), return_when=asyncio.FIRST_COMPLETED,
            timeout=timeout,
        )
        if not done:
            # hedge-delay elapsed with everything still in flight:
            # back up (if the global rate cap still has budget)
            if self.take_hedge():
                launch_hedge()
            return []
        out = []
        for t in done:
            key, hedged = self.pending.pop(t)
            out.append((key, hedged, t))
        return out

    def note_success(self, hedged: bool) -> None:
        if hedged and self.health is not None:
            self.health.record_hedge_win()
            registry().inc("rpc_hedge_win", endpoint=self.label)

    def cancel_pending(self, cancel: bool = True) -> None:
        """Consume-then-cancel every straggler (or just consume when
        the caller wants writes to converge in the background)."""
        for t in self.pending:
            # consume first: a task that completed with an error
            # between the last wait and this cleanup is immune to
            # cancel and would log "never retrieved"
            t.add_done_callback(_consume_task_result)
            if cancel:
                t.cancel()


def named_rpc_error(e: Exception, node: bytes, endpoint_path: str) -> RpcError:
    """Wrap a transport/handler error so the surfaced message names the
    peer and endpoint. The original exception rides along as __cause__
    and the structured fields as attributes."""
    who = node.hex()[:8] if node else "?"
    err = RpcError(
        f"{endpoint_path} -> node {who}: {type(e).__name__}: {e}")
    err.node = node
    err.endpoint = endpoint_path
    err.__cause__ = e
    return err


@dataclass
class RequestStrategy:
    """ref: rpc_helper.rs RequestStrategy."""

    quorum: int = 1
    prio: int = PRIO_NORMAL
    timeout: float = DEFAULT_TIMEOUT
    send_all_at_once: bool = False
    interrupt_stragglers: bool = True  # reads cancel; writes let them finish
    # None = the cluster-wide default (PeerHealthTracker.hedging_enabled);
    # True/False forces it for this call (bench A/B, writes that must
    # never duplicate)
    hedge: Optional[bool] = None
    # per-request consistency override (ISSUE 16): DEGRADED lets THIS
    # read serve from the surviving zones during a zone partition
    # (effective quorum 1) while the cluster default stays consistent;
    # None = use strategy.quorum as given
    consistency: Optional[ConsistencyMode] = None
    # required distinct zones per write set: None = derive from the
    # current layout's zone_redundancy; 0 = skip the check explicitly
    zone_span: Optional[int] = None


class QuorumSetResultTracker:
    """Per-set success/failure accounting over possibly-overlapping quorum
    sets (ref: rpc_helper.rs:665-766)."""

    def __init__(self, sets: list[list[bytes]], quorum: int):
        self.sets = sets
        self.quorum = quorum
        self.nodes: list[bytes] = []
        seen = set()
        for s in sets:
            for n in s:
                if n not in seen:
                    seen.add(n)
                    self.nodes.append(n)
        self.successes: dict[bytes, Any] = {}
        self.failures: dict[bytes, Exception] = {}

    def success(self, node: bytes, resp) -> None:
        self.successes[node] = resp
        # a hedged retry can land after its sibling attempt failed; the
        # key IS written, so the stale failure must not keep counting
        # against the set (a key in both maps inflates the failure
        # count and can raise a spurious QuorumError)
        self.failures.pop(node, None)

    def failure(self, node: bytes, err: Exception) -> None:
        if node not in self.successes:
            self.failures[node] = err

    def set_counts(self) -> list[tuple[int, int]]:
        """(successes, failures) per set."""
        return [
            (
                sum(1 for n in s if n in self.successes),
                sum(1 for n in s if n in self.failures),
            )
            for s in self.sets
        ]

    def all_quorums_ok(self) -> bool:
        return all(ok >= self.quorum for ok, _ in self.set_counts())

    def too_many_failures(self) -> bool:
        return any(
            fail > len(s) - self.quorum
            for s, (_, fail) in zip(self.sets, self.set_counts())
        )

    def quorum_error(self) -> QuorumError:
        return QuorumError(
            quorum=self.quorum,
            sets=len(self.sets),
            ok=len(self.successes),
            total=len(self.nodes),
            errors=[str(e) for e in self.failures.values()],
        )


class RpcHelper:
    def __init__(self, system: System):
        self.system = system
        self.netapp = system.netapp

    def health(self):
        """The shared PeerHealthTracker, or None on bare test stubs."""
        peering = getattr(self.system, "peering", None)
        return getattr(peering, "health", None)

    # ---- node ordering (ref: rpc_helper.rs:621-660) --------------------

    def request_order(self, nodes: list[bytes]) -> list[bytes]:
        """self first; then nodes in partitioned zones last, breaker
        state (open/exhausted peers behind healthy), same-zone, ping.

        The same-zone rank is what makes reads local-zone-FIRST: the
        initial `quorum` launches land in-zone whenever enough local
        replicas exist, and hedges walk the order into other zones only
        when the local ones stall — cross-WAN reads are the fallback,
        not the default. The partitioned-zone rank (zones/health.py)
        exists because a severed link flaps: reconnect succeeds, the
        first frame dies, and for that window conn state + breaker both
        look healthy while every call into the zone will fail."""
        my_zone = None
        role = self.system.layout_helper.current().node_role(self.netapp.id)
        if role is not None:
            my_zone = role.zone
        health = self.health()
        zone_health = getattr(self.system, "zone_health", None)
        dead_zones = (zone_health.partitioned_zones()
                      if zone_health is not None else set())
        now = time.monotonic()

        def key(n: bytes):
            if n == self.netapp.id:
                return (0, 0, 0, 0, 0.0)
            role = self.system.layout_helper.current().node_role(n)
            same_zone = role is not None and my_zone is not None and role.zone == my_zone
            partitioned = (role is not None and bool(role.zone)
                           and role.zone in dead_zones)
            ping = self.system.peering.ping_avg(n)
            connected = self.system.is_up(n)
            brk = health.breaker_rank(n, now) if health is not None else 0
            return (
                1,
                1 if partitioned else 0,
                brk,
                1 if (same_zone and connected) else (2 if connected else 3),
                ping if ping is not None else 1.0,
            )

        return sorted(nodes, key=key)

    # ---- single call ---------------------------------------------------

    async def _tracked_call(
        self,
        endpoint,
        node: bytes,
        payload,
        prio: int,
        timeout: Optional[float],
        stream=None,
    ):
        """endpoint.call with the self-healing bookkeeping: adaptive
        per-peer timeout, half-open probe accounting, success/failure
        recording, and peer+endpoint-named errors. Returns the raw
        (resp, reply_stream) pair."""
        health = self.health()
        if health is not None:
            timeout = health.call_timeout(node, timeout)
            health.note_launch(node)
        t0 = time.monotonic()
        try:
            resp, rstream = await endpoint.call(
                node, payload, prio, stream=stream, timeout=timeout
            )
        except asyncio.CancelledError:
            # a cancelled hedge loser is not a peer failure
            raise
        except Exception as e:
            if health is not None:
                health.record_failure(node, time.monotonic() - t0)
            raise named_rpc_error(e, node, endpoint.path) from e
        if health is not None:
            health.record_success(node, time.monotonic() - t0)
        return resp, rstream

    async def call(
        self,
        endpoint,
        node: bytes,
        payload,
        prio: int = PRIO_NORMAL,
        timeout: float = DEFAULT_TIMEOUT,
        stream=None,
    ):
        resp, rstream = await self._tracked_call(
            endpoint, node, payload, prio, timeout, stream=stream
        )
        return (resp, rstream) if rstream is not None else resp

    # ---- try_call_many (ref: rpc_helper.rs:290-411) --------------------

    async def try_call_many(
        self,
        endpoint,
        nodes: list[bytes],
        payload,
        strategy: RequestStrategy,
        make_payload: Optional[Callable[[bytes], Any]] = None,
    ) -> list:
        """Returns >= quorum successful responses or raises QuorumError.

        With send_all_at_once=False the adaptive send is HEDGED: when no
        in-flight request completes within the peers' observed p95, the
        next-ranked node gets a backup request immediately — a hung peer
        costs one hedge delay, not its whole timeout. First success
        wins; with interrupt_stragglers the losers are cancelled."""
        quorum = strategy.quorum
        if strategy.consistency == ConsistencyMode.DEGRADED and quorum > 1:
            # per-request sloppy read: any one replica answers — the
            # caller chose availability over read-your-writes for THIS
            # request (a zone is partitioned and the consistent quorum
            # would need it)
            registry().inc("rpc_degraded_read", endpoint=endpoint.path)
            quorum = 1
        if quorum > len(nodes):
            raise QuorumError(quorum, 1, 0, len(nodes), ["not enough nodes"])
        order = self.request_order(list(nodes))
        race = HedgedRace(
            self.health(), endpoint.path,
            enabled=(False if strategy.send_all_at_once
                     else strategy.hedge))
        successes: list = []
        errors: list[Exception] = []
        next_i = 0

        def launch_one(hedged: bool = False):
            nonlocal next_i
            node = order[next_i]
            next_i += 1
            pl = make_payload(node) if make_payload else payload
            race.launch(node, self._tracked_call(
                endpoint, node, pl, strategy.prio, strategy.timeout),
                hedged)

        n_initial = len(order) if strategy.send_all_at_once else min(quorum, len(order))
        for _ in range(n_initial):
            launch_one()
        try:
            while len(successes) < quorum:
                if not race.pending:
                    raise QuorumError(
                        quorum, 1, len(successes), len(nodes), [str(e) for e in errors]
                    )
                done = await race.wait(
                    can_hedge=next_i < len(order),
                    launch_hedge=lambda: launch_one(hedged=True))
                for node, hedged, t in done:
                    try:
                        resp, _stream = t.result()
                        successes.append((node, resp))
                        race.note_success(hedged)
                    except Exception as e:
                        errors.append(e)
                        if next_i < len(order):
                            launch_one()
            return [r for _, r in successes]
        finally:
            # interrupt_stragglers: reads cancel the losers; writes are
            # left running so replicas converge — either way the result
            # is consumed so a late failure doesn't log "never
            # retrieved"
            race.cancel_pending(cancel=strategy.interrupt_stragglers)

    # ---- zone-span verification (ISSUE 16) -----------------------------

    def _verify_zone_span(self, endpoint, write_sets, strategy,
                          node_of) -> None:
        """Pre-flight: every write set must span the required number of
        distinct zones, else raise the typed ZoneSpanError BEFORE any
        replica is written. `strategy.zone_span` overrides (0 = skip);
        None derives the requirement from the current layout's
        zone_redundancy. Conservative by design: a set containing a
        node with no zone in the current layout (old-version member
        mid-transition, zoneless test stub) is skipped rather than
        failed — the check exists to catch mis-spread placement, not to
        wedge transitions. A DEGRADED-override write also skips it: the
        caller already chose availability over placement guarantees."""
        if strategy.zone_span == 0 \
                or strategy.consistency == ConsistencyMode.DEGRADED:
            return
        layout = self.system.layout_helper.current()
        required = strategy.zone_span
        if required is None:
            zr = getattr(layout, "zone_redundancy", None)
            if zr == "maximum":
                all_zones = set()
                for n in layout.storage_nodes():
                    role = layout.node_role(n)
                    if role is not None and role.zone:
                        all_zones.add(role.zone)
                required = min(layout.replication_factor, len(all_zones))
            elif isinstance(zr, int):
                required = zr
            else:
                return
        if required <= 1:
            return
        for s in write_sets:
            zones = set()
            for key in s:
                role = layout.node_role(node_of(key))
                if role is None or not role.zone:
                    zones = None
                    break
                zones.add(role.zone)
            if zones is None:
                continue
            if len(zones) < required:
                registry().inc("rpc_zone_span_reject",
                               endpoint=endpoint.path)
                raise ZoneSpanError(required, len(zones), sorted(zones),
                                    len(s))

    # ---- try_write_many_sets (ref: rpc_helper.rs:413-538) --------------

    async def try_write_many_sets(
        self,
        endpoint,
        write_sets: list[list],
        payload,
        strategy: RequestStrategy,
        make_payload: Optional[Callable[[Any], Any]] = None,
        make_stream: Optional[Callable[[Any], Any]] = None,
        make_call: Optional[Callable[[Any], Any]] = None,
    ) -> QuorumSetResultTracker:
        """Write to every set with per-set quorum; left-over requests keep
        running in the background after success (so all replicas converge
        without blocking the caller).

        Set entries are opaque quorum keys — normally node ids, but e.g.
        the erasure block path uses (node, shard_index) tuples with a
        `make_call` that issues the per-key RPC itself.

        strategy.hedge=True opts the write into BACKUP PUSHES: a quorum
        key still unanswered past its holder's observed p95 gets the
        same call re-issued, first landing wins. Only idempotent writes
        may opt in (content-addressed shard/block puts); GL02 flags
        every hedge=True site so the justification is reviewable, and
        the `[rpc] hedge_writes` knob can disable the behavior
        cluster-wide."""
        tracker = QuorumSetResultTracker(write_sets, strategy.quorum)
        if not tracker.nodes:
            # empty/unassigned layout: fail fast instead of hanging on a
            # future no task will ever resolve
            raise tracker.quorum_error()
        result = asyncio.get_event_loop().create_future()
        health = self.health()

        def node_of(key) -> bytes:
            # quorum keys are node ids, or (node, shard_index) tuples on
            # the erasure path
            return key[0] if isinstance(key, tuple) else key

        self._verify_zone_span(endpoint, write_sets, strategy, node_of)

        async def one(key, hedged: bool = False):
            t0 = time.monotonic()
            try:
                if make_call is not None:
                    resp, _ = await make_call(key)
                else:
                    pl = make_payload(key) if make_payload else payload
                    st = make_stream(key) if make_stream else None
                    resp, _ = await endpoint.call(
                        key, pl, strategy.prio, stream=st,
                        timeout=strategy.timeout
                    )
                if health is not None:
                    health.record_success(node_of(key),
                                          time.monotonic() - t0)
                if hedged and key not in tracker.successes:
                    race.note_success(True)
                tracker.success(key, resp)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                if health is not None:
                    health.record_failure(node_of(key),
                                          time.monotonic() - t0)
                if not isinstance(e, RpcError) \
                        or not hasattr(e, "node"):
                    e = named_rpc_error(e, node_of(key), endpoint.path)
                # a hedged attempt is a bonus try: its failure must not
                # count against the key while the original is still in
                # flight (same invariant as read hedges — "losers are
                # not counted as failures"), or a fast-failing backup
                # raises a spurious QuorumError on a write the original
                # lands moments later
                if not hedged:
                    tracker.failure(key, e)
            if not result.done():
                if tracker.all_quorums_ok():
                    result.set_result(True)
                elif tracker.too_many_failures():
                    result.set_exception(tracker.quorum_error())

        # writes default to UNHEDGED (hedge=None stays off): only an
        # explicit, GL02-audited hedge=True — and the cluster knob —
        # arm the backup pushes
        race = HedgedRace(
            health, endpoint.path,
            enabled=(strategy.hedge is True and health is not None
                     and health.write_hedging_enabled))

        async def hedge_backups():
            """Re-issue the slowest still-pending write once it is past
            its holder's observed p95 — the write-path analog of the
            read hedge. The re-issued call races its sibling; the
            tracker keeps whichever lands (idempotent by contract)."""
            while not result.done() and race.hedging \
                    and race.hedges < race.max_hedges:
                waiting = [k for k in tracker.nodes
                           if k not in tracker.successes
                           and k not in tracker.failures]
                if not waiting:
                    return
                await asyncio.sleep(
                    health.hedge_delay(node_of(k) for k in waiting))
                if result.done():
                    return
                still = [k for k in waiting
                         if k not in tracker.successes
                         and k not in tracker.failures]
                if not still:
                    continue
                if not race.take_hedge():
                    return
                ht = asyncio.create_task(one(still[0], hedged=True))
                ht._garage_background = True  # same write-behind rule
                tasks.append(ht)

        tasks = [asyncio.create_task(one(n)) for n in tracker.nodes]
        for t in tasks:
            # on quorum success the stragglers deliberately keep
            # writing in the background (write-behind to the rest of
            # the set) — not leaks for the sanitizer
            t._garage_background = True
        hedge_task = (asyncio.create_task(hedge_backups())
                      if race.hedging else None)
        try:
            await result
            return tracker
        except BaseException:
            for t in tasks:
                t.cancel()
            raise
        finally:
            if hedge_task is not None:
                hedge_task.add_done_callback(_consume_task_result)
                hedge_task.cancel()
        # on success, remaining tasks continue in background by design
