"""Membership, cluster layout, and quorum RPC.

Ref parity: src/rpc/ (SURVEY.md §2.4). The layer that turns the net/
transport mesh into a cluster: gossip-based membership (system.py), the
partition ring with max-flow optimal assignment (layout/), quorum call
orchestration (rpc_helper.py), and the replication-mode plugin boundary
(replication_mode.py) — extended here with the erasure(k, m) mode whose
math runs on TPU (ops/rs.py).
"""

from .replication_mode import ConsistencyMode, ReplicationMode  # noqa: F401
from .system import System  # noqa: F401
from .rpc_helper import RpcHelper, RequestStrategy  # noqa: F401
