"""External peer discovery: Consul catalog and Kubernetes CRD.

Ref parity: src/rpc/consul.rs:230 (agent service registration +
catalog lookup) and src/rpc/kubernetes.rs:114 (GarageNode custom
resources). Providers publish this node's (id, rpc addr) and return the
set of advertised peers; System's discovery loop merges them into the
peering manager alongside bootstrap peers, so nodes find each other on
elastic platforms without static peer lists.

HTTP is stdlib urllib driven through asyncio.to_thread — discovery is
low-rate control traffic and must not add client library dependencies.
"""

from __future__ import annotations

import asyncio
import json
import logging
import ssl
import urllib.request
from typing import Optional

log = logging.getLogger("garage_tpu.rpc.discovery")

Peer = tuple[tuple[str, int], Optional[bytes]]


class DiscoveryProvider:
    async def register(self, node_id: bytes, addr: tuple[str, int]) -> None:
        raise NotImplementedError

    async def get_peers(self) -> list[Peer]:
        raise NotImplementedError


def _http(method: str, url: str, body: Optional[dict] = None,
          headers: Optional[dict] = None,
          ctx: Optional[ssl.SSLContext] = None,
          timeout: float = 10.0) -> tuple[int, bytes]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("content-type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=ctx) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class ConsulDiscovery(DiscoveryProvider):
    """ref: rpc/consul.rs — agent service register + catalog service
    lookup; the node id travels in the service meta."""

    def __init__(self, consul_http_addr: str, service_name: str,
                 tags: Optional[list[str]] = None):
        self.base = consul_http_addr.rstrip("/")
        if not self.base.startswith("http"):
            self.base = "http://" + self.base
        self.service_name = service_name
        self.tags = tags or []

    async def register(self, node_id: bytes, addr: tuple[str, int]) -> None:
        payload = {
            "Name": self.service_name,
            "ID": f"{self.service_name}-{node_id.hex()[:16]}",
            "Address": addr[0],
            "Port": addr[1],
            "Tags": self.tags,
            "Meta": {"node_id": node_id.hex()},
        }
        status, body = await asyncio.to_thread(
            _http, "PUT", f"{self.base}/v1/agent/service/register", payload)
        if status != 200:
            raise RuntimeError(
                f"consul register failed: {status} {body[:200]!r}")

    async def get_peers(self) -> list[Peer]:
        status, body = await asyncio.to_thread(
            _http, "GET",
            f"{self.base}/v1/catalog/service/{self.service_name}")
        if status != 200:
            raise RuntimeError(
                f"consul catalog failed: {status} {body[:200]!r}")
        out: list[Peer] = []
        for svc in json.loads(body.decode()):
            host = svc.get("ServiceAddress") or svc.get("Address")
            port = svc.get("ServicePort")
            if not host or not port:
                continue
            nid = None
            meta = svc.get("ServiceMeta") or {}
            if meta.get("node_id"):
                try:
                    nid = bytes.fromhex(meta["node_id"])
                except ValueError:
                    pass
            out.append(((host, int(port)), nid))
        return out


class KubernetesDiscovery(DiscoveryProvider):
    """ref: rpc/kubernetes.rs — GarageNode custom resources in a
    namespace; each node upserts its own CR and lists the others. Runs
    with the in-pod service account by default."""

    GROUP = "deuxfleurs.fr"
    VERSION = "v1"
    PLURAL = "garagenodes"

    def __init__(self, namespace: str, service_name: str,
                 api_server: Optional[str] = None,
                 token: Optional[str] = None,
                 ca_cert: Optional[str] = None):
        self.namespace = namespace
        self.service_name = service_name
        self.api = (api_server or
                    "https://kubernetes.default.svc").rstrip("/")
        self._token = token
        self._ca = ca_cert
        self._ctx: Optional[ssl.SSLContext] = None

    def _headers(self) -> dict:
        token = self._token
        if token is None:
            try:
                with open("/var/run/secrets/kubernetes.io/serviceaccount"
                          "/token") as f:
                    token = f.read().strip()
            except OSError:
                token = ""
        return {"authorization": f"Bearer {token}"} if token else {}

    def _ssl(self) -> Optional[ssl.SSLContext]:
        if not self.api.startswith("https"):
            return None
        if self._ctx is None:
            ca = self._ca or ("/var/run/secrets/kubernetes.io/"
                              "serviceaccount/ca.crt")
            try:
                self._ctx = ssl.create_default_context(cafile=ca)
            except (OSError, ssl.SSLError):
                self._ctx = ssl.create_default_context()
        return self._ctx

    def _url(self, name: str = "") -> str:
        base = (f"{self.api}/apis/{self.GROUP}/{self.VERSION}"
                f"/namespaces/{self.namespace}/{self.PLURAL}")
        return f"{base}/{name}" if name else base

    async def register(self, node_id: bytes, addr: tuple[str, int]) -> None:
        name = f"{self.service_name}-{node_id.hex()[:16]}"
        cr = {
            "apiVersion": f"{self.GROUP}/{self.VERSION}",
            "kind": "GarageNode",
            "metadata": {"name": name},
            "spec": {"hostname": addr[0], "port": addr[1],
                     "nodeId": node_id.hex()},
        }
        # _headers() reads the service-account token file — build it
        # INSIDE the worker thread (as a to_thread argument it would
        # evaluate on the loop before the hop)
        status, body = await asyncio.to_thread(
            lambda: _http("PUT", self._url(name), cr, self._headers(),
                          self._ssl()))
        if status == 404:  # CR does not exist yet: create
            status, body = await asyncio.to_thread(
                lambda: _http("POST", self._url(), cr, self._headers(),
                              self._ssl()))
        if status not in (200, 201):
            raise RuntimeError(
                f"kubernetes register failed: {status} {body[:200]!r}")

    async def get_peers(self) -> list[Peer]:
        status, body = await asyncio.to_thread(
            lambda: _http("GET", self._url(), None, self._headers(),
                          self._ssl()))
        if status != 200:
            raise RuntimeError(
                f"kubernetes list failed: {status} {body[:200]!r}")
        out: list[Peer] = []
        for item in json.loads(body.decode()).get("items", []):
            spec = item.get("spec") or {}
            host, port = spec.get("hostname"), spec.get("port")
            if not host or not port:
                continue
            nid = None
            if spec.get("nodeId"):
                try:
                    nid = bytes.fromhex(spec["nodeId"])
                except ValueError:
                    pass
            out.append(((host, int(port)), nid))
        return out


def providers_from_config(config) -> list[DiscoveryProvider]:
    out: list[DiscoveryProvider] = []
    if getattr(config, "consul_http_addr", None):
        out.append(ConsulDiscovery(
            config.consul_http_addr,
            getattr(config, "consul_service_name", None) or "garage",
        ))
    if getattr(config, "kubernetes_namespace", None):
        out.append(KubernetesDiscovery(
            config.kubernetes_namespace,
            getattr(config, "kubernetes_service_name", None) or "garage",
        ))
    return out
