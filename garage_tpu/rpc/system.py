"""System: membership, node status gossip, cluster health.

Ref parity: src/rpc/system.rs:87-965. Owns the node identity key, the
peering manager, the layout manager, the persisted peer list, the
status-exchange loop (every 10 s), and ClusterHealth computation from
per-partition quorum counts.
"""

from __future__ import annotations

import asyncio
import logging
import os
import platform
import shutil
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ..net import NetApp, PeeringManager
from ..net.message import PRIO_HIGH
from ..net.netapp import gen_node_key, node_key_from_bytes, node_key_to_bytes
from ..net.peering import PeerConnState
from ..utils.background import spawn
from ..utils.migrate import Migratable
from ..utils.persister import Persister
from .layout.manager import LayoutManager
from .layout.version import N_PARTITIONS
from .replication_mode import ConsistencyMode, ReplicationMode

log = logging.getLogger("garage_tpu.rpc.system")

STATUS_EXCHANGE_INTERVAL = 10.0
DISCOVERY_INTERVAL = 60.0


class ClusterHealthStatus(Enum):
    HEALTHY = "healthy"
    DEGRADED = "degraded"
    UNAVAILABLE = "unavailable"


@dataclass
class ClusterHealth:
    """ref: src/rpc/system.rs:150-179"""

    status: ClusterHealthStatus
    known_nodes: int
    connected_nodes: int
    storage_nodes: int
    storage_nodes_up: int
    partitions: int
    partitions_quorum: int
    partitions_all_ok: int


@dataclass
class NodeStatus:
    hostname: str = ""
    replication_factor: int = 0
    layout_digest: bytes = b""
    meta_disk_avail: Optional[tuple[int, int]] = None  # (avail, total)
    data_disk_avail: Optional[tuple[int, int]] = None

    def pack(self):
        return {
            "hostname": self.hostname,
            "rf": self.replication_factor,
            "layout": self.layout_digest,
            "meta_disk": self.meta_disk_avail,
            "data_disk": self.data_disk_avail,
        }

    @classmethod
    def unpack(cls, o):
        return cls(
            o.get("hostname", ""),
            o.get("rf", 0),
            bytes(o.get("layout", b"")),
            tuple(o["meta_disk"]) if o.get("meta_disk") else None,
            tuple(o["data_disk"]) if o.get("data_disk") else None,
        )


@dataclass
class KnownNode:
    id: bytes
    addr: Optional[tuple]
    is_up: bool
    last_seen_secs_ago: Optional[float]
    status: Optional[NodeStatus]


class PeerList(Migratable):
    """Persisted peer addresses for rediscovery after restart."""

    VERSION_MARKER = b"GTpeers1"

    def __init__(self, peers: Optional[list] = None):
        self.peers = peers or []  # [(node_id, addr_tuple)]

    def pack(self):
        return [[n, list(a)] for n, a in self.peers]

    @classmethod
    def unpack(cls, raw):
        return cls([(bytes(n), tuple(a)) for n, a in raw])


def load_or_gen_node_key(meta_dir: str):
    """ref: src/rpc/system.rs:181-238 (key in metadata dir)."""
    os.makedirs(meta_dir, exist_ok=True)
    path = os.path.join(meta_dir, "node_key")
    if os.path.exists(path):
        with open(path, "rb") as f:
            return node_key_from_bytes(f.read())
    key = gen_node_key()
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(node_key_to_bytes(key))
    return key


class System:
    """Membership manager + composition point for the rpc layer."""

    def __init__(
        self,
        netapp: NetApp,
        replication: ReplicationMode,
        meta_dir: str,
        data_dirs: Optional[list[str]] = None,
        bootstrap_peers: Optional[list] = None,
        status_interval: float = STATUS_EXCHANGE_INTERVAL,
        ping_interval: Optional[float] = None,
        discovery: Optional[list] = None,
        discovery_interval: float = 60.0,
    ):
        self.netapp = netapp
        self.replication = replication
        self.meta_dir = meta_dir
        self.data_dirs = data_dirs or []
        self.id = netapp.id
        self.status_interval = status_interval

        os.makedirs(meta_dir, exist_ok=True)
        self.peer_list_persister = Persister(meta_dir, "peer_list", PeerList)
        self._last_persisted_peers: Optional[list] = None
        persisted = self.peer_list_persister.load()
        bootstrap = list(bootstrap_peers or [])
        if persisted is not None:
            bootstrap += [(addr, nid) for nid, addr in persisted.peers]
        kwargs = {}
        if ping_interval is not None:
            kwargs = {"ping_interval": ping_interval, "retry_interval": ping_interval}
        self.peering = PeeringManager(netapp, bootstrap, **kwargs)

        self.discovery = list(discovery or [])
        self.discovery_interval = discovery_interval

        self.layout_manager = LayoutManager(netapp, meta_dir, replication)
        self.node_status: dict[bytes, tuple[float, NodeStatus]] = {}

        # per-zone health rollup (garage_tpu/zones/): stateless
        # derivation over peering + layout, serves GET /v1/zones and
        # the zone-aware quorum strategy's partitioned-zone checks
        from ..zones import ZoneHealth

        self.zone_health = ZoneHealth(self)

        self.ep = netapp.endpoint("garage_rpc/system").set_handler(self._handle)
        netapp.on_connected.append(self._on_peer_connected)
        self._stop = asyncio.Event()
        self._tasks: list[asyncio.Task] = []

    @property
    def layout_helper(self):
        return self.layout_manager.helper

    # ---- lifecycle -----------------------------------------------------

    async def run(self) -> None:
        if self.netapp.bind_addr is not None and self.netapp.local_net is None:
            await self.netapp.listen()
        self._tasks = [
            asyncio.create_task(self.peering.run()),
            asyncio.create_task(self._status_exchange_loop()),
        ]
        if self.discovery:
            self._tasks.append(
                asyncio.create_task(self._discovery_loop()))
        for t in self._tasks:
            # supervised service loops (cancelled right below on stop):
            # not leaks for the runtime sanitizer's teardown check
            t._garage_background = True
        await self._stop.wait()
        await self.peering.stop()
        for t in self._tasks:
            t.cancel()
        await self.netapp.shutdown()

    async def stop(self) -> None:
        self._stop.set()

    # ---- status gossip -------------------------------------------------

    def local_status(self) -> NodeStatus:
        def disk(path_list):
            tot = avail = 0
            for p in path_list:
                try:
                    u = shutil.disk_usage(p)
                    tot += u.total
                    avail += u.free
                except OSError:
                    pass
            return (avail, tot) if tot else None

        return NodeStatus(
            hostname=platform.node(),
            replication_factor=self.replication.factor,
            layout_digest=self.layout_manager.digest(),
            meta_disk_avail=disk([self.meta_dir]),
            data_disk_avail=disk(self.data_dirs),
        )

    async def _discovery_loop(self) -> None:
        """Publish ourself and pull peers from external providers
        (Consul catalog / Kubernetes CRDs; ref: rpc/system.rs:627
        discovery_loop). Providers are advisory: failures log and the
        loop keeps going on bootstrap + gossip."""
        while True:
            addr = self.netapp.public_addr
            for prov in self.discovery:
                name = type(prov).__name__
                try:
                    if addr is not None:
                        await prov.register(self.id, addr)
                    for peer_addr, nid in await prov.get_peers():
                        if nid == self.id or (nid is None
                                              and peer_addr == addr):
                            continue
                        self.peering.add_peer(tuple(peer_addr), nid)
                except Exception as e:
                    log.info("discovery via %s failed: %s", name, e)
            await asyncio.sleep(self.discovery_interval)

    async def _status_exchange_loop(self) -> None:
        while True:
            try:
                await self._advertise_status()
            except Exception:
                log.exception("status exchange failed")
            await asyncio.sleep(self.status_interval)

    async def _advertise_status(self) -> None:
        status = self.local_status().pack()
        peers = list(self.netapp.conns.keys())

        async def one(p):
            try:
                resp, _ = await self.ep.call(
                    p, {"op": "status", "status": status}, PRIO_HIGH, timeout=10.0
                )
                if resp.get("layout") is not None:
                    self.layout_manager.merge_remote(resp["layout"])
            except Exception as e:
                log.debug("status exchange with %s failed: %s", p[:4].hex(), e)

        await asyncio.gather(*(one(p) for p in peers))
        self._persist_peer_list()

    def _persist_peer_list(self) -> None:
        peers = sorted(
            (p.id, p.addr)
            for p in self.peering.peers.values()
            if p.id != self.id and p.addr is not None
        )
        # skip the write+fsync+rename when membership hasn't changed (this
        # runs on the 10 s status-exchange loop)
        if peers == self._last_persisted_peers:
            return
        self.peer_list_persister.save(PeerList(peers))
        self._last_persisted_peers = peers

    def _on_peer_connected(self, peer_id: bytes, incoming: bool) -> None:
        # push our layout to newly connected peers so they converge fast
        async def push():
            try:
                await self.layout_manager.pull_from(peer_id)
                raw = None
                from ..utils.migrate import encode as menc

                raw = menc(self.layout_manager.history)
                await self.layout_manager._advertise_one(peer_id, raw)
            except Exception as e:
                log.debug("layout push to %s failed: %s",
                          peer_id[:4].hex(), e)

        spawn(push(), "layout-push-on-connect")

    # ---- rpc handler ---------------------------------------------------

    async def _handle(self, from_node, payload, stream):
        op = payload.get("op")
        if op == "status":
            st = NodeStatus.unpack(payload["status"])
            self.node_status[from_node] = (time.monotonic(), st)
            reply = {}
            if st.layout_digest != self.layout_manager.digest():
                from ..utils.migrate import encode as menc

                reply["layout"] = menc(self.layout_manager.history)
            return reply
        if op == "get_known_nodes":
            return {
                "nodes": [
                    [n.id, list(n.addr) if n.addr else None, n.is_up]
                    for n in self.get_known_nodes()
                ]
            }
        if op == "connect":
            addr = tuple(payload["addr"])
            pid = payload.get("id")
            await self.netapp.try_connect(addr, bytes(pid) if pid else None)
            return {}
        raise ValueError(f"unknown system op {op}")

    # ---- queries -------------------------------------------------------

    def is_up(self, node: bytes) -> bool:
        if node == self.id:
            return True
        p = self.peering.peers.get(node)
        return p is not None and p.state == PeerConnState.CONNECTED

    def get_known_nodes(self) -> list[KnownNode]:
        out = []
        for p in self.peering.get_peer_list():
            status = self.node_status.get(p.id)
            out.append(
                KnownNode(
                    id=p.id,
                    addr=p.addr,
                    is_up=(p.id == self.id) or p.state == PeerConnState.CONNECTED,
                    last_seen_secs_ago=(
                        time.monotonic() - p.last_seen if p.last_seen else None
                    ),
                    status=status[1] if status else None,
                )
            )
        return out

    def health(self) -> ClusterHealth:
        """ref: src/rpc/system.rs:430-510."""
        history = self.layout_manager.history
        storage_nodes = history.all_storage_nodes()
        storage_up = {n for n in storage_nodes if self.is_up(n)}

        rq = self.replication.read_quorum
        wq = self.replication.write_quorum
        quorum_ok = 0
        all_ok = 0
        for p in range(N_PARTITIONS):
            sets = [v.nodes_of(p) for v in history.versions]
            sets = [s for s in sets if s]
            if not sets:
                continue
            ups = [sum(1 for n in s if self.is_up(n)) for s in sets]
            if all(u >= wq for u in ups) and any(u >= rq for u in ups):
                quorum_ok += 1
            if all(u == len(s) for u, s in zip(ups, sets)):
                all_ok += 1

        peers = self.peering.get_peer_list()
        connected = sum(
            1 for p in peers if p.state in (PeerConnState.CONNECTED, PeerConnState.OURSELF)
        )
        if not history.current().ring_assignment_data:
            status = ClusterHealthStatus.UNAVAILABLE
        elif quorum_ok == N_PARTITIONS:
            status = (
                ClusterHealthStatus.HEALTHY
                if all_ok == N_PARTITIONS and len(storage_up) == len(storage_nodes)
                else ClusterHealthStatus.DEGRADED
            )
        else:
            status = ClusterHealthStatus.UNAVAILABLE
        return ClusterHealth(
            status=status,
            known_nodes=len(peers),
            connected_nodes=connected,
            storage_nodes=len(storage_nodes),
            storage_nodes_up=len(storage_up),
            partitions=N_PARTITIONS,
            partitions_quorum=quorum_ok,
            partitions_all_ok=all_ok,
        )
