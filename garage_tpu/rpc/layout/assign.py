"""Optimal partition assignment via max-flow + movement minimization.

Ref parity: src/rpc/layout/version.rs:281-400 (orchestration). Same
guarantees, independent implementation:

1. The optimal partition size is found by binary search: size s is
   feasible iff a flow network routes N_PARTITIONS * rf units through
   Source -> partition (cap rf) -> (partition, zone) (cap rf-zr+1)
   -> node (cap 1 per partition; floor(capacity/s) total) -> Sink.
   Larger s = fewer partitions per node; the max feasible s uses the
   cluster's capacity most evenly under the zone constraint.
2. With s fixed, data movement is minimized by giving cost 0 to
   (partition -> node) edges present in the previous layout and cost 1
   to new ones, then cancelling negative cycles until the flow is
   min-cost.
3. Zone spread is maximized BEYOND the zone_redundancy floor: the
   (partition -> zone) edge is split into a cap-1 cost-0 slot plus a
   cap-(rf-zr+1-1) slot costing SPREAD_COST per doubled replica, with
   SPREAD_COST > the largest possible total movement cost so the
   min-cost pass lexicographically prefers one-replica-per-zone
   placements and only then minimizes movement. Without this, a
   6-node/3-zone rf=3 zr=2 cluster legally doubles two replicas of
   every partition into one zone — losing that zone then kills
   R=2/W=2 quorums even though zone_redundancy=2 was satisfied.

`check_against_naive` (tests/test_layout.py) mirrors the reference's
optimality test: the computed partition size must be >= a naive greedy
assignment's.
"""

from __future__ import annotations

from typing import Optional

from .graph import FlowGraph
from .version import N_PARTITIONS, LayoutVersion, NodeRole

SRC, SINK = "src", "sink"

# Cost of placing a second/third replica of a partition into a zone
# that already holds one. Movement cost totals at most
# N_PARTITIONS * rf (= 768 at rf 3), so any value above that makes
# spread maximization strictly dominate movement minimization.
SPREAD_COST = 1024


class LayoutError(Exception):
    pass


def _zone_redundancy_value(zone_redundancy, zones: list[str], rf: int) -> int:
    if zone_redundancy == "maximum":
        return min(rf, len(zones))
    zr = int(zone_redundancy)
    if zr < 1 or zr > rf:
        raise LayoutError(f"zone_redundancy {zr} out of range 1..{rf}")
    return zr


def _build_graph(
    storage: list[tuple[bytes, NodeRole]],
    zones: list[str],
    rf: int,
    zr: int,
    size: int,
    prev_edges: Optional[set[tuple[int, int]]] = None,
) -> FlowGraph:
    g = FlowGraph()
    per_zone_cap = rf - zr + 1
    costed = prev_edges is not None
    for p in range(N_PARTITIONS):
        g.add_edge(SRC, ("p", p), rf)
        for z in set(z for z in zones):
            if costed and per_zone_cap > 1:
                # parallel edges: the first replica in a zone is free,
                # every doubled one costs SPREAD_COST — min-cost flow
                # then spreads replicas across zones whenever capacity
                # allows, with zr still the hard feasibility floor
                g.add_edge(("p", p), ("pz", p, z), 1, 0)
                g.add_edge(("p", p), ("pz", p, z), per_zone_cap - 1,
                           SPREAD_COST)
            else:
                g.add_edge(("p", p), ("pz", p, z), per_zone_cap)
    for i, (node, role) in enumerate(storage):
        for p in range(N_PARTITIONS):
            cost = 0 if costed and (p, i) in prev_edges else 1
            g.add_edge(("pz", p, role.zone), ("n", i), 1, cost if costed else 0)
        g.add_edge(("n", i), SINK, role.capacity // size if size > 0 else 0)
    return g


def compute_assignment(
    roles_items: list[tuple[bytes, Optional[NodeRole]]],
    rf: int,
    zone_redundancy,
    prev: Optional[LayoutVersion] = None,
) -> tuple[list[bytes], bytes, int]:
    """Returns (node_id_vec, ring_assignment_data, partition_size).

    roles_items: (node_id, role) pairs; role None or capacity None are
    excluded from storage (gateways).
    """
    storage = [
        (node, role)
        for node, role in roles_items
        if role is not None and role.capacity is not None
    ]
    storage.sort(key=lambda kv: kv[0])
    if len(storage) < rf:
        raise LayoutError(
            f"not enough storage nodes: {len(storage)} < replication factor {rf}"
        )
    zones = sorted({role.zone for _, role in storage})
    zr = _zone_redundancy_value(zone_redundancy, zones, rf)
    if len(zones) < zr:
        raise LayoutError(f"only {len(zones)} zones < zone redundancy {zr}")

    # previous assignment as (partition, storage-index) pairs for
    # movement minimization
    prev_edges: set[tuple[int, int]] = set()
    if prev is not None and prev.ring_assignment_data:
        index_of = {node: i for i, (node, _) in enumerate(storage)}
        for p in range(N_PARTITIONS):
            for node in prev.nodes_of(p):
                i = index_of.get(node)
                if i is not None:
                    prev_edges.add((p, i))

    target = N_PARTITIONS * rf

    def feasible(size: int) -> bool:
        g = _build_graph(storage, zones, rf, zr, size)
        return g.max_flow(SRC, SINK) == target

    # binary search the largest feasible partition size; coarsened to
    # ~2^12 candidate sizes so the number of max-flow runs stays bounded
    # (sub-unit precision of the partition size has no operational value)
    hi = sum(role.capacity for _, role in storage) // target + 1
    unit = max(1, hi >> 12)
    lo = 1
    if not feasible(lo):
        raise LayoutError("cluster capacity too small for even one byte per partition")
    lo_u, hi_u = 0, hi // unit
    while lo_u < hi_u:
        mid = (lo_u + hi_u + 1) // 2
        if feasible(max(1, mid * unit)):
            lo_u = mid
        else:
            hi_u = mid - 1
    size = max(1, lo_u * unit)

    # min-movement flow at the optimal size
    g = _build_graph(storage, zones, rf, zr, size, prev_edges)
    if g.max_flow(SRC, SINK) != target:
        raise LayoutError("internal: optimal size infeasible on costed graph")
    g.cancel_negative_cycles()

    # extract assignment
    node_id_vec = [node for node, _ in storage]
    ring = bytearray()
    for p in range(N_PARTITIONS):
        chosen = []
        for i, (node, role) in enumerate(storage):
            # find the (pz -> n) edge for this partition/node
            for e in g.adj[g.vertex(("pz", p, role.zone))]:
                if e % 2 == 0 and g.to[e] == g.vertex(("n", i)) and g.flow_on(e) > 0:
                    chosen.append(i)
                    break
        if len(chosen) != rf:
            raise LayoutError(f"partition {p}: assigned {len(chosen)} != rf {rf}")
        ring.extend(chosen)
    return node_id_vec, bytes(ring), size
