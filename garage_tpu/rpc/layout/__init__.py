"""Cluster layout: the partition ring and its optimal assignment.

Ref parity: src/rpc/layout/ (SURVEY.md §2.4). 256 partitions (top 8 bits
of the blake2 item hash) are assigned to storage nodes by a max-flow
computation that provably maximizes the feasible partition size under
zone-redundancy constraints, then minimizes data movement from the
previous layout by cancelling negative-cost cycles. Multiple layout
versions stay live during a rebalance; CRDT update trackers gossip each
node's ack/sync progress and drive old-version garbage collection.
"""

from .version import LayoutVersion, NodeRole, PARTITION_BITS, N_PARTITIONS  # noqa: F401
from .history import LayoutHistory, UpdateTrackers, LayoutStaging  # noqa: F401
from .helper import LayoutHelper  # noqa: F401
from .manager import LayoutManager  # noqa: F401
from .transition import ResizeOrchestrator, ResizeReport, ResizeStuck  # noqa: F401
