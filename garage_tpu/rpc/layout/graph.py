"""Flow-network algorithms for layout assignment.

Ref parity: src/rpc/layout/graph_algo.rs:14-405, re-implemented from the
textbook algorithms (Dinic blocking-flow max-flow; Bellman-Ford
negative-cycle cancellation for min-cost refinement). Graphs are small —
O(256 + 256*zones + nodes) vertices — so pure Python is plenty; this is
operator-triggered control-plane work, not the data plane.
"""

from __future__ import annotations

from collections import deque
from typing import Hashable


class FlowGraph:
    """Integer-capacity flow network with optional per-edge costs."""

    def __init__(self):
        self.ids: dict[Hashable, int] = {}
        self.adj: list[list[int]] = []  # vertex -> edge indices
        self.to: list[int] = []
        self.cap: list[int] = []
        self.cost: list[int] = []

    def vertex(self, key: Hashable) -> int:
        v = self.ids.get(key)
        if v is None:
            v = self.ids[key] = len(self.adj)
            self.adj.append([])
        return v

    def add_edge(self, u: Hashable, v: Hashable, cap: int, cost: int = 0) -> int:
        """Returns the forward edge index; the reverse edge is index^1."""
        ui, vi = self.vertex(u), self.vertex(v)
        e = len(self.to)
        self.to.extend([vi, ui])
        self.cap.extend([cap, 0])
        self.cost.extend([cost, -cost])
        self.adj[ui].append(e)
        self.adj[vi].append(e + 1)
        return e

    def flow_on(self, e: int) -> int:
        """Units pushed over forward edge e (== residual of its twin)."""
        return self.cap[e + 1] if e % 2 == 0 else self.cap[e]

    # ---- Dinic max-flow ------------------------------------------------

    def max_flow(self, s: Hashable, t: Hashable) -> int:
        si, ti = self.vertex(s), self.vertex(t)
        total = 0
        n = len(self.adj)
        while True:
            level = [-1] * n
            level[si] = 0
            q = deque([si])
            while q:
                u = q.popleft()
                for e in self.adj[u]:
                    v = self.to[e]
                    if self.cap[e] > 0 and level[v] < 0:
                        level[v] = level[u] + 1
                        q.append(v)
            if level[ti] < 0:
                return total
            it = [0] * n

            def dfs(u: int, pushed: int) -> int:
                if u == ti:
                    return pushed
                while it[u] < len(self.adj[u]):
                    e = self.adj[u][it[u]]
                    v = self.to[e]
                    if self.cap[e] > 0 and level[v] == level[u] + 1:
                        got = dfs(v, min(pushed, self.cap[e]))
                        if got > 0:
                            self.cap[e] -= got
                            self.cap[e ^ 1] += got
                            return got
                    it[u] += 1
                return 0

            while True:
                pushed = dfs(si, 1 << 62)
                if pushed == 0:
                    break
                total += pushed

    # ---- negative-cycle cancellation ----------------------------------

    def cancel_negative_cycles(self) -> int:
        """Repeatedly find negative-cost cycles in the residual graph
        and push flow around them. Returns total cost reduction.
        Terminates because each batch strictly reduces the (integer)
        total cost.

        The old one-cycle-per-full-Bellman-Ford version was measured at
        70+ SECONDS of event-loop-blocking CPU on an unlucky 5-node
        resize (129 cycles x O(V*E) each): every pred-graph sweep now
        harvests ALL vertex-disjoint cycles, and detection fires on the
        first pass whose relaxations close a pred loop instead of after
        |V| full passes."""
        reduced = 0
        while True:
            cycles = self._find_negative_cycles()
            if not cycles:
                return reduced
            for cyc in cycles:
                # vertex-disjoint cycles cannot share an edge or its
                # twin (twins share both endpoints), so batch-mates
                # never consume each other's residual capacity
                push = min(self.cap[e] for e in cyc)
                for e in cyc:
                    self.cap[e] -= push
                    self.cap[e ^ 1] += push
                reduced += -sum(self.cost[e] for e in cyc) * push

    def _find_negative_cycles(self) -> list[list[int]]:
        """Bellman-Ford from a virtual all-zeros super-source with
        early detection: a cycle formed by the predecessor pointers at
        ANY point during relaxation is a negative cycle (the standard
        invariant — dist only decreases, so a pred loop sums < 0), so
        the pred graph is swept after every pass and every
        vertex-disjoint cycle found is returned at once. [] once no
        negative cycle remains."""
        n = len(self.adj)
        dist = [0] * n
        pred = [-1] * n
        nedge = len(self.to)
        for _ in range(n + 1):
            updated = False
            for e in range(nedge):
                if self.cap[e] <= 0:
                    continue
                u = self.to[e ^ 1]
                d = dist[u] + self.cost[e]
                v = self.to[e]
                if d < dist[v]:
                    dist[v] = d
                    pred[v] = e
                    updated = True
            if not updated:
                return []
            cycles = self._pred_cycles(pred)
            if cycles:
                return cycles
        return []  # |V|+1 updating passes without a pred loop: cannot
        # happen with integer costs, but fail closed rather than spin

    def _pred_cycles(self, pred: list[int]) -> list[list[int]]:
        """All vertex-disjoint cycles in the predecessor graph, each as
        its residual-edge list ({pred[x] for x on the loop}). Iteration
        is in vertex-index order, so results are deterministic."""
        n = len(self.adj)
        color = [0] * n  # 0 unvisited / 1 on current walk / 2 done
        out: list[list[int]] = []
        for start in range(n):
            if color[start] or pred[start] < 0:
                continue
            path: list[int] = []
            v = start
            while True:
                if color[v] == 1:
                    # v repeats inside the current walk: pred loop.
                    # Disjointness is structural: the pred graph is
                    # functional (≤1 pred edge per vertex), so cycles
                    # can't share a vertex, and a harvested loop's
                    # vertices are colored 2 below — later walks break
                    # before re-reaching them.
                    loop = path[path.index(v):]
                    cyc = [pred[x] for x in loop]
                    # the invariant guarantees negativity; the check
                    # guards termination against any edge case (a
                    # zero-cost loop would spin forever)
                    if sum(self.cost[e] for e in cyc) < 0:
                        out.append(cyc)
                    break
                if color[v] == 2 or pred[v] < 0:
                    break
                color[v] = 1
                path.append(v)
                v = self.to[pred[v] ^ 1]
            for x in path:
                color[x] = 2
        return out
