"""Flow-network algorithms for layout assignment.

Ref parity: src/rpc/layout/graph_algo.rs:14-405, re-implemented from the
textbook algorithms (Dinic blocking-flow max-flow; Bellman-Ford
negative-cycle cancellation for min-cost refinement). Graphs are small —
O(256 + 256*zones + nodes) vertices — so pure Python is plenty; this is
operator-triggered control-plane work, not the data plane.
"""

from __future__ import annotations

from collections import deque
from typing import Hashable


class FlowGraph:
    """Integer-capacity flow network with optional per-edge costs."""

    def __init__(self):
        self.ids: dict[Hashable, int] = {}
        self.adj: list[list[int]] = []  # vertex -> edge indices
        self.to: list[int] = []
        self.cap: list[int] = []
        self.cost: list[int] = []

    def vertex(self, key: Hashable) -> int:
        v = self.ids.get(key)
        if v is None:
            v = self.ids[key] = len(self.adj)
            self.adj.append([])
        return v

    def add_edge(self, u: Hashable, v: Hashable, cap: int, cost: int = 0) -> int:
        """Returns the forward edge index; the reverse edge is index^1."""
        ui, vi = self.vertex(u), self.vertex(v)
        e = len(self.to)
        self.to.extend([vi, ui])
        self.cap.extend([cap, 0])
        self.cost.extend([cost, -cost])
        self.adj[ui].append(e)
        self.adj[vi].append(e + 1)
        return e

    def flow_on(self, e: int) -> int:
        """Units pushed over forward edge e (== residual of its twin)."""
        return self.cap[e + 1] if e % 2 == 0 else self.cap[e]

    # ---- Dinic max-flow ------------------------------------------------

    def max_flow(self, s: Hashable, t: Hashable) -> int:
        si, ti = self.vertex(s), self.vertex(t)
        total = 0
        n = len(self.adj)
        while True:
            level = [-1] * n
            level[si] = 0
            q = deque([si])
            while q:
                u = q.popleft()
                for e in self.adj[u]:
                    v = self.to[e]
                    if self.cap[e] > 0 and level[v] < 0:
                        level[v] = level[u] + 1
                        q.append(v)
            if level[ti] < 0:
                return total
            it = [0] * n

            def dfs(u: int, pushed: int) -> int:
                if u == ti:
                    return pushed
                while it[u] < len(self.adj[u]):
                    e = self.adj[u][it[u]]
                    v = self.to[e]
                    if self.cap[e] > 0 and level[v] == level[u] + 1:
                        got = dfs(v, min(pushed, self.cap[e]))
                        if got > 0:
                            self.cap[e] -= got
                            self.cap[e ^ 1] += got
                            return got
                    it[u] += 1
                return 0

            while True:
                pushed = dfs(si, 1 << 62)
                if pushed == 0:
                    break
                total += pushed

    # ---- negative-cycle cancellation ----------------------------------

    def cancel_negative_cycles(self) -> int:
        """Repeatedly find a negative-cost cycle in the residual graph and
        push one unit around it. Returns total cost reduction. Terminates
        because each pass strictly reduces the (integer) total cost."""
        reduced = 0
        while True:
            cyc = self._find_negative_cycle()
            if cyc is None:
                return reduced
            push = min(self.cap[e] for e in cyc)
            for e in cyc:
                self.cap[e] -= push
                self.cap[e ^ 1] += push
            reduced += -sum(self.cost[e] for e in cyc) * push

    def _find_negative_cycle(self):
        """Bellman-Ford over residual edges; returns edge list of a
        negative cycle or None."""
        n = len(self.adj)
        dist = [0] * n  # virtual super-source: all zeros
        pred_edge = [-1] * n
        x = -1
        for _ in range(n):
            x = -1
            for e in range(len(self.to)):
                if self.cap[e] <= 0:
                    continue
                u = self.to[e ^ 1]
                v = self.to[e]
                if dist[u] + self.cost[e] < dist[v]:
                    dist[v] = dist[u] + self.cost[e]
                    pred_edge[v] = e
                    x = v
            if x == -1:
                return None
        # x is on or reachable from a negative cycle; walk back n steps
        for _ in range(n):
            x = self.to[pred_edge[x] ^ 1]
        cyc = []
        v = x
        while True:
            e = pred_edge[v]
            cyc.append(e)
            v = self.to[e ^ 1]
            if v == x:
                break
        cyc.reverse()
        return cyc
