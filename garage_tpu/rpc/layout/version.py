"""One version of the cluster layout: roles + ring assignment.

Ref parity: src/rpc/layout/mod.rs:37-94,240-287 and version.rs:101-130.
256 partitions = top 8 bits of the blake2 item hash; the ring is a flat
array of node indices, replication_factor entries per partition.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ...utils import crdt
from ...utils.migrate import Migratable

PARTITION_BITS = 8
N_PARTITIONS = 1 << PARTITION_BITS


def partition_of(hash32: bytes) -> int:
    """Top 8 bits of the item/block hash (ref: layout/version.rs:101)."""
    return hash32[0]


@dataclass
class NodeRole:
    """Operator-assigned role of a node (ref: layout/mod.rs:84-94).
    capacity None = gateway node: serves APIs, stores nothing."""

    zone: str = ""
    capacity: Optional[int] = None
    tags: list = field(default_factory=list)

    def pack(self):
        return [self.zone, self.capacity, list(self.tags)]

    @classmethod
    def unpack(cls, raw):
        return cls(raw[0], raw[1], list(raw[2]))


def pack_roles(roles: crdt.LwwMap) -> list:
    """LwwMap[node_id -> NodeRole|None] to plain structure."""
    return [
        [k, lww.ts, None if lww.value is None else lww.value.pack()]
        for k, lww in roles.items_lww()
    ]


def unpack_roles(raw: list) -> crdt.LwwMap:
    return crdt.LwwMap(
        {
            bytes(k): crdt.Lww(ts, None if v is None else NodeRole.unpack(v))
            for k, ts, v in raw
        }
    )


class LayoutVersion(Migratable):
    """Immutable once created; new versions come from apply_staged."""

    VERSION_MARKER = b"GTlayv01"

    def __init__(
        self,
        version: int,
        replication_factor: int,
        zone_redundancy,  # int or "maximum"
        roles: crdt.LwwMap,
        node_id_vec: list[bytes],
        ring_assignment_data: bytes,
        partition_size: int,
    ):
        self.version = version
        self.replication_factor = replication_factor
        self.zone_redundancy = zone_redundancy
        self.roles = roles  # node_id -> Lww[NodeRole|None] (None = removed)
        self.node_id_vec = node_id_vec
        self.ring_assignment_data = ring_assignment_data
        self.partition_size = partition_size

    # ---- queries -------------------------------------------------------

    def nodes_of(self, partition: int) -> list[bytes]:
        """Storage nodes of a partition, in ring order
        (ref: layout/version.rs:117)."""
        rf = self.replication_factor
        if len(self.ring_assignment_data) != N_PARTITIONS * rf:
            return []
        base = partition * rf
        return [
            self.node_id_vec[self.ring_assignment_data[base + i]] for i in range(rf)
        ]

    def nodes_of_hash(self, hash32: bytes) -> list[bytes]:
        return self.nodes_of(partition_of(hash32))

    def storage_nodes(self) -> set[bytes]:
        """Nodes with a storage role (capacity set) in this version."""
        return {
            n
            for n, r in self.roles.items()
            if r is not None and r.capacity is not None
        }

    def all_nodes(self) -> set[bytes]:
        """Every node with a role (incl. gateways)."""
        return {n for n, r in self.roles.items() if r is not None}

    def node_role(self, node: bytes) -> Optional[NodeRole]:
        return self.roles.get(node)

    # ---- serialization -------------------------------------------------

    def pack(self):
        return {
            "version": self.version,
            "rf": self.replication_factor,
            "zr": self.zone_redundancy,
            "roles": pack_roles(self.roles),
            "nodes": list(self.node_id_vec),
            "ring": self.ring_assignment_data,
            "psize": self.partition_size,
        }

    @classmethod
    def unpack(cls, o):
        return cls(
            o["version"],
            o["rf"],
            o["zr"],
            unpack_roles(o["roles"]),
            [bytes(n) for n in o["nodes"]],
            bytes(o["ring"]),
            o["psize"],
        )
