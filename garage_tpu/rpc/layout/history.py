"""Layout history: live versions, update trackers, staged changes.

Ref parity: src/rpc/layout/history.rs + mod.rs:235-478. During a
rebalance several LayoutVersions are live at once: writes go to ALL
their write sets, reads prefer the newest. Three gossiped CRDT trackers
(per-node monotonic version counters) drive convergence:

  ack_map      — node acks version v: it directs writes to v's sets
  sync_map     — node has fully synced/offloaded its data for v
  sync_ack_map — node has seen that sync quorum was reached for v

Versions older than min(sync_ack) are garbage collected (kept in
old_versions, <= 5, for block lookups during long resyncs —
ref: mod.rs:235).
"""

from __future__ import annotations

from typing import Optional

from ...utils import crdt
from ...utils.data import blake2sum
from ...utils.migrate import Migratable, encode as migrate_encode
from .assign import compute_assignment
from .version import (
    LayoutVersion,
    NodeRole,
    pack_roles,
    unpack_roles,
)

OLD_VERSION_COUNT = 5


class UpdateTrackers:
    """Three per-node monotonic version maps; merge = pointwise max."""

    def __init__(self, ack=None, sync=None, sync_ack=None):
        self.ack: dict[bytes, int] = dict(ack or {})
        self.sync: dict[bytes, int] = dict(sync or {})
        self.sync_ack: dict[bytes, int] = dict(sync_ack or {})

    @staticmethod
    def _merge_map(a: dict, b: dict) -> dict:
        out = dict(a)
        for k, v in b.items():
            out[k] = max(out.get(k, 0), v)
        return out

    def merge(self, other: "UpdateTrackers") -> "UpdateTrackers":
        return UpdateTrackers(
            self._merge_map(self.ack, other.ack),
            self._merge_map(self.sync, other.sync),
            self._merge_map(self.sync_ack, other.sync_ack),
        )

    def set_max(self, which: str, node: bytes, version: int) -> bool:
        m = getattr(self, which)
        if m.get(node, 0) < version:
            m[node] = version
            return True
        return False

    @staticmethod
    def min_among(m: dict, nodes: set[bytes], min_version: int) -> int:
        return min((m.get(n, min_version) for n in nodes), default=min_version)

    def pack(self):
        return [
            sorted(self.ack.items()),
            sorted(self.sync.items()),
            sorted(self.sync_ack.items()),
        ]

    @classmethod
    def unpack(cls, raw):
        return cls(
            {bytes(k): v for k, v in raw[0]},
            {bytes(k): v for k, v in raw[1]},
            {bytes(k): v for k, v in raw[2]},
        )


class LayoutStaging:
    """Staged role changes + parameters, CRDT-merged across operators."""

    def __init__(self, parameters: Optional[crdt.Lww] = None, roles: Optional[crdt.LwwMap] = None):
        # parameters value: {"zone_redundancy": int | "maximum"}
        self.parameters = parameters or crdt.Lww.new({"zone_redundancy": "maximum"})
        self.roles = roles or crdt.LwwMap()

    def merge(self, other: "LayoutStaging") -> "LayoutStaging":
        return LayoutStaging(
            self.parameters.merge(other.parameters),
            self.roles.merge(other.roles),
        )

    def pack(self):
        return [self.parameters.pack(), pack_roles(self.roles)]

    @classmethod
    def unpack(cls, raw):
        return cls(crdt.Lww.unpack(raw[0]), unpack_roles(raw[1]))


class LayoutHistory(Migratable):
    VERSION_MARKER = b"GTlayh01"

    def __init__(
        self,
        replication_factor: int,
        versions: Optional[list[LayoutVersion]] = None,
        old_versions: Optional[list[LayoutVersion]] = None,
        update_trackers: Optional[UpdateTrackers] = None,
        staging: Optional[LayoutStaging] = None,
    ):
        self.replication_factor = replication_factor
        self.versions = versions or []
        self.old_versions = old_versions or []
        self.update_trackers = update_trackers or UpdateTrackers()
        self.staging = staging or LayoutStaging()

    @classmethod
    def new(cls, replication_factor: int) -> "LayoutHistory":
        v0 = LayoutVersion(0, replication_factor, "maximum", crdt.LwwMap(), [], b"", 0)
        return cls(replication_factor, versions=[v0])

    # ---- queries -------------------------------------------------------

    def current(self) -> LayoutVersion:
        return self.versions[-1]

    def min_stored(self) -> int:
        return self.versions[0].version

    def get_version(self, v: int) -> Optional[LayoutVersion]:
        for lv in self.versions:
            if lv.version == v:
                return lv
        for lv in self.old_versions:
            if lv.version == v:
                return lv
        return None

    def all_storage_nodes(self) -> set[bytes]:
        out = set()
        for v in self.versions:
            out |= v.storage_nodes()
        return out

    def all_nongateway_nodes(self) -> set[bytes]:
        return self.all_storage_nodes()

    def digest(self) -> bytes:
        """Digest for gossip comparison. Excludes old_versions: they are
        node-local bookkeeping (never merged), so including them would
        make digests permanently diverge between nodes and re-send the
        full layout on every status exchange."""
        import msgpack

        o = self.pack()
        del o["old"]
        return blake2sum(msgpack.packb(o, use_bin_type=True))

    # ---- staging -------------------------------------------------------

    def stage_role(self, node: bytes, role: Optional[NodeRole]) -> None:
        self.staging = LayoutStaging(
            self.staging.parameters, self.staging.roles.insert(node, role)
        )

    def stage_parameters(self, zone_redundancy) -> None:
        self.staging = LayoutStaging(
            self.staging.parameters.update({"zone_redundancy": zone_redundancy}),
            self.staging.roles,
        )

    def staged_roles(self) -> crdt.LwwMap:
        """Current roles with staged changes applied on top."""
        return self.current().roles.merge(self.staging.roles)

    def compute_staged_changes(self, version: Optional[int] = None,
                               staging: Optional["LayoutStaging"] = None,
                               ) -> LayoutVersion:
        """Compute the next LayoutVersion (max-flow assignment) from
        current roles + staged changes WITHOUT installing it — pure
        CPU work, safe to run in a worker thread so an expensive
        assignment never blocks the serving loop (ref: history.rs:270).
        Off-loop callers MUST pass the `staging` snapshot they pinned:
        reading the live self.staging from the worker thread would tear
        against a concurrent stage call — and install_version's
        `consumed` check only protects the clear, not the compute
        input."""
        if staging is None:
            staging = self.staging
        next_version = self.current().version + 1
        if version is not None and version != next_version:
            raise ValueError(
                f"expected version {next_version}, operator said {version} "
                "(layout changed concurrently?)"
            )
        roles = self.current().roles.merge(staging.roles)
        zr = staging.parameters.value.get("zone_redundancy", "maximum")
        node_id_vec, ring, psize = compute_assignment(
            list(roles.items()), self.replication_factor, zr, prev=self.current()
        )
        return LayoutVersion(
            next_version, self.replication_factor, zr, roles,
            node_id_vec, ring, psize,
        )

    def install_version(self, lv: LayoutVersion,
                        consumed: Optional[LayoutStaging] = None) -> None:
        """Append a computed LayoutVersion; refuses a stale compute
        (layout changed while the assignment ran). Staging is cleared
        only when it is still the `consumed` snapshot the compute read
        — a role staged DURING an off-loop compute must survive into
        the next apply, not be silently discarded."""
        if lv.version != self.current().version + 1:
            raise ValueError(
                f"computed layout v{lv.version} is stale: current is "
                f"v{self.current().version} (layout changed concurrently)")
        self.versions.append(lv)
        if consumed is None or consumed is self.staging:
            self.staging = LayoutStaging(
                crdt.Lww.new({"zone_redundancy": lv.zone_redundancy}),
                crdt.LwwMap(),
            )
        # else: staging changed mid-compute — keep it whole (already-
        # applied entries make the next apply a cheap near-no-op; a
        # lost staged role would be unrecoverable)
        self.cleanup_old_versions()

    def apply_staged_changes(self, version: Optional[int] = None) -> None:
        """Synchronous compute + install. ref: history.rs:270."""
        staged = self.staging
        self.install_version(self.compute_staged_changes(version),
                             consumed=staged)

    def revert_staged_changes(self) -> None:
        # drop staged PARAMETERS too: reverting restores the current
        # version's zone_redundancy, not whatever was staged
        zr = self.current().zone_redundancy
        self.staging = LayoutStaging(crdt.Lww.new({"zone_redundancy": zr}), crdt.LwwMap())

    # ---- merge + GC ----------------------------------------------------

    def merge(self, other: "LayoutHistory") -> bool:
        """CRDT merge; returns True if anything changed."""
        changed = False
        known = {v.version for v in self.versions}
        if other.versions:
            # adopt versions newer than ours
            for v in other.versions:
                if v.version not in known and v.version > self.current().version:
                    self.versions.append(v)
                    changed = True
            self.versions.sort(key=lambda v: v.version)
        merged_trackers = self.update_trackers.merge(other.update_trackers)
        if (
            merged_trackers.ack != self.update_trackers.ack
            or merged_trackers.sync != self.update_trackers.sync
            or merged_trackers.sync_ack != self.update_trackers.sync_ack
        ):
            self.update_trackers = merged_trackers
            changed = True
        merged_staging = self.staging.merge(other.staging)
        if (
            merged_staging.parameters != self.staging.parameters
            or merged_staging.roles != self.staging.roles
        ):
            self.staging = merged_staging
            changed = True
        if self.cleanup_old_versions():
            changed = True
        return changed

    def cleanup_old_versions(self) -> bool:
        """Drop versions fully sync-acked by every storage node; leading
        invalid versions (no storage nodes, e.g. the empty bootstrap v0)
        go as soon as a valid one exists (ref: history.rs:79-115)."""
        changed = False
        if self.current().storage_nodes():
            # invalid leading versions (no storage nodes) are discarded
            # outright, not archived — they hold no data anyone reads
            # (ref: history.rs:80-89)
            while len(self.versions) > 1 and not self.versions[0].storage_nodes():
                self.versions.pop(0)
                changed = True
        while len(self.versions) > 1:
            v = self.versions[0].version
            # only the CURRENT version's nodes gate GC: nodes removed by a
            # newer layout are being discarded and must not pin old
            # versions forever (ref: history.rs:94-108 ASSUMPTION)
            nodes = self.current().storage_nodes()
            min_sync_ack = UpdateTrackers.min_among(
                self.update_trackers.sync_ack, nodes, self.min_stored()
            )
            if nodes and min_sync_ack > v:
                self.old_versions.append(self.versions.pop(0))
                changed = True
            else:
                break
        while len(self.old_versions) > OLD_VERSION_COUNT:
            self.old_versions.pop(0)
            changed = True
        return changed

    # ---- serialization -------------------------------------------------

    def pack(self):
        return {
            "rf": self.replication_factor,
            "versions": [v.pack() for v in self.versions],
            "old": [v.pack() for v in self.old_versions],
            "trackers": self.update_trackers.pack(),
            "staging": self.staging.pack(),
        }

    @classmethod
    def unpack(cls, o):
        return cls(
            o["rf"],
            [LayoutVersion.unpack(v) for v in o["versions"]],
            [LayoutVersion.unpack(v) for v in o["old"]],
            UpdateTrackers.unpack(o["trackers"]),
            LayoutStaging.unpack(o["staging"]),
        )
