"""LayoutHelper: version-aware read/write target selection + ack lock.

Ref parity: src/rpc/layout/helper.rs:30-49 and manager.rs:338-381. The
subtle core of layout transitions:

- writes go to the write sets of EVERY version >= ack_map_min, so no
  window exists where old and new quorums disagree;
- reads go to the newest version all storage nodes have synced, so a
  read quorum always intersects the write quorums that stored the data;
- a node only advances its ack tracker once its in-flight writes pinned
  to older versions drain (the ack lock), so the cluster never abandons
  a write set that still has writes in flight.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from .history import LayoutHistory, UpdateTrackers
from .version import LayoutVersion, partition_of


class LayoutHelper:
    def __init__(self, history: LayoutHistory, node_id: bytes):
        self.history = history
        self.node_id = node_id
        self._ack_inflight: dict[int, int] = {}  # layout version -> writes

    # ---- tracker mins --------------------------------------------------

    def _storage_nodes(self) -> set[bytes]:
        return self.history.all_storage_nodes()

    def ack_map_min(self) -> int:
        return UpdateTrackers.min_among(
            self.history.update_trackers.ack,
            self._storage_nodes(),
            self.history.min_stored(),
        )

    def sync_map_min(self) -> int:
        return UpdateTrackers.min_among(
            self.history.update_trackers.sync,
            self._storage_nodes(),
            self.history.min_stored(),
        )

    # ---- read/write target selection ----------------------------------

    def current(self) -> LayoutVersion:
        return self.history.current()

    def versions_for_writes(self) -> list[LayoutVersion]:
        amin = self.ack_map_min()
        return [v for v in self.history.versions if v.version >= amin]

    def read_version(self) -> LayoutVersion:
        """Newest version whose data migration is complete everywhere."""
        smin = self.sync_map_min()
        best = self.history.versions[0]
        for v in self.history.versions:
            if v.version <= smin:
                best = v
        return best

    def write_sets_of(self, hash32: bytes) -> list[list[bytes]]:
        """One write set per live version (ref: helper.rs write_sets_of)."""
        sets = []
        for v in self.versions_for_writes():
            s = v.nodes_of_hash(hash32)
            if s and s not in sets:
                sets.append(s)
        return sets

    def read_nodes_of(self, hash32: bytes) -> list[bytes]:
        return self.read_version().nodes_of_hash(hash32)

    def current_storage_nodes_of(self, hash32: bytes) -> list[bytes]:
        return self.current().nodes_of_hash(hash32)

    def storage_sets_of(self, partition: int) -> list[list[bytes]]:
        sets = []
        for v in self.versions_for_writes():
            s = v.nodes_of(partition)
            if s and s not in sets:
                sets.append(s)
        return sets

    def block_read_nodes_of(self, hash32: bytes) -> list[bytes]:
        """All candidate holders, newest layout first, then old versions
        (ref: rpc_helper.rs:570-619)."""
        out: list[bytes] = []
        p = partition_of(hash32)
        for v in reversed(self.history.versions + self.history.old_versions):
            for n in v.nodes_of(p):
                if n not in out:
                    out.append(n)
        return out

    # ---- ack lock ------------------------------------------------------

    @contextlib.contextmanager
    def write_lock(self):
        """Pin the current version set for the duration of a write; on
        release, advance our ack tracker as far as in-flight writes
        allow (ref: manager.rs:344-381)."""
        v = self.current().version
        self._ack_inflight[v] = self._ack_inflight.get(v, 0) + 1
        try:
            yield self.versions_for_writes()
        finally:
            self._ack_inflight[v] -= 1
            if self._ack_inflight[v] == 0:
                del self._ack_inflight[v]
            self.advance_ack()

    def advance_ack(self) -> bool:
        """ack[self] := oldest version still carrying in-flight writes,
        or the current version if none."""
        target = min(self._ack_inflight, default=self.current().version)
        return self.history.update_trackers.set_max("ack", self.node_id, target)

    # ---- sync trackers (driven by table/block syncers) -----------------

    def sync_until(self, version: int) -> bool:
        return self.history.update_trackers.set_max("sync", self.node_id, version)

    def advance_sync_ack(self) -> bool:
        return self.history.update_trackers.set_max(
            "sync_ack", self.node_id, self.sync_map_min()
        )
