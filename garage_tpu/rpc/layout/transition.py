"""Resize orchestrator: stage -> apply -> ack -> sync -> commit, live.

The layout layer (manager/history/helper) makes a cluster resize SAFE —
writes go to the union of every live version's write sets, reads to the
newest fully-synced version — but nothing in the tree actually DROVE a
transition end to end. This module is that driver (ISSUE 6 tentpole):
it sequences one staged change through its four phases against live
traffic and reports where a stuck transition is stuck.

Phases (all observed through the gossiped CRDT trackers, so the
orchestrator runs identically over TCP or the in-process loopback
cluster used by tests/bench):

  apply    compute the new LayoutVersion from the staged roles
           (max-flow assignment) and broadcast it.
  ack      every storage node directs writes to the new version's
           write sets: ack_map_min >= v. Until then writes fan out to
           BOTH versions' sets (helper.write_sets_of) — the
           union-quorum window where no request may fail for lack of a
           stable layout.
  sync     every storage node has migrated its data: sync_map_min >= v.
           Per node that means every registered sync source — each
           table's anti-entropy round AND the block store's rebalance
           backlog — reports completion (LayoutManager.sync_until_from
           takes the minimum across sources).
  commit   the superseded version is GC'd (min_stored >= v) once
           sync_ack converges; block reads still consult old_versions
           for stragglers.

The orchestrator never mutates remote nodes directly: staging is a
CRDT merge, progress is gossip. Its only powers are local staging,
apply, broadcast nudges, and patience.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from ...utils.metrics import registry
from .version import NodeRole

log = logging.getLogger("garage_tpu.rpc.layout.transition")


@dataclass
class ResizeReport:
    """What one transition did and how long each phase took."""

    version: int = 0
    phase_seconds: dict = field(default_factory=dict)  # phase -> s
    laggards: dict = field(default_factory=dict)  # phase -> [node hex]
    completed: bool = False

    @property
    def total_seconds(self) -> float:
        return sum(self.phase_seconds.values())


class ResizeStuck(TimeoutError):
    """A phase did not converge in time; names the phase and the nodes
    whose trackers are behind, so the operator knows WHOM to kick."""

    def __init__(self, phase: str, version: int, laggards: list[str]):
        super().__init__(
            f"resize to layout v{version} stuck in phase {phase!r}; "
            f"lagging nodes: {', '.join(laggards) or '(none visible)'}")
        self.phase = phase
        self.version = version
        self.laggards = laggards


class ResizeOrchestrator:
    """Drives one staged layout change on a coordinator node's System."""

    def __init__(self, system, poll_s: float = 0.05):
        self.system = system
        self.lm = system.layout_manager
        self.helper = system.layout_manager.helper
        self.poll_s = poll_s

    # ---- staging (thin sugar over the CRDT staging map) -----------------

    def stage_add(self, node_id: bytes, zone: str, capacity: int) -> None:
        self.lm.history.stage_role(
            node_id, NodeRole(zone=zone, capacity=capacity))

    def stage_remove(self, node_id: bytes) -> None:
        self.lm.history.stage_role(node_id, None)

    # ---- the transition -------------------------------------------------

    async def apply(self, version: Optional[int] = None) -> int:
        """Apply staged changes -> new version, broadcast. Returns the
        new version number (operators pass the expected one to refuse
        racing a concurrent change). The assignment computation runs
        off the event loop — an unlucky movement-minimization graph
        costs seconds of CPU, and freezing the serving loop for it
        would BE the downtime this orchestrator exists to avoid."""
        await self.lm.apply_staged_async(version)
        return self.helper.current().version

    async def run(self, timeout: float = 60.0,
                  expect_version: Optional[int] = None) -> ResizeReport:
        """Apply the staged change and wait out all four phases."""
        report = ResizeReport()
        t0 = time.monotonic()
        report.version = v = await self.apply(expect_version)
        dt = time.monotonic() - t0
        report.phase_seconds["apply"] = dt
        # recorded like the wait phases below so the admin /v1/resize
        # readout shows all four phases, not just the waits
        registry().observe("resize_phase_seconds", dt, phase="apply")
        for phase, waiter in (("ack", self.wait_acked),
                              ("sync", self.wait_synced),
                              ("commit", self.wait_committed)):
            t0 = time.monotonic()
            await waiter(v, timeout)
            dt = time.monotonic() - t0
            report.phase_seconds[phase] = dt
            registry().observe("resize_phase_seconds", dt, phase=phase)
        report.completed = True
        registry().inc("resize_transitions_completed")
        log.info("layout v%d transition complete in %.2fs "
                 "(ack %.2fs, sync %.2fs, commit %.2fs)",
                 v, report.total_seconds,
                 report.phase_seconds["ack"],
                 report.phase_seconds["sync"],
                 report.phase_seconds["commit"])
        return report

    async def wait_acked(self, version: int, timeout: float = 30.0) -> None:
        await self._wait(
            "ack", version,
            lambda: self.helper.ack_map_min() >= version,
            lambda: self._laggards("ack", version), timeout)

    async def wait_synced(self, version: int, timeout: float = 60.0) -> None:
        await self._wait(
            "sync", version,
            lambda: self.helper.sync_map_min() >= version,
            lambda: self._laggards("sync", version), timeout)

    async def wait_committed(self, version: int,
                             timeout: float = 60.0) -> None:
        await self._wait(
            "commit", version,
            lambda: self.lm.history.min_stored() >= version,
            lambda: self._laggards("sync_ack", version), timeout)

    # ---- internals ------------------------------------------------------

    def _laggards(self, tracker: str, version: int) -> list[str]:
        m = getattr(self.lm.history.update_trackers, tracker)
        out = []
        for n in sorted(self.lm.history.all_storage_nodes()):
            if m.get(n, self.lm.history.min_stored()) < version:
                out.append(n.hex()[:8])
        return out

    async def _wait(self, phase: str, version: int, cond, laggards,
                    timeout: float) -> None:
        deadline = time.monotonic() + timeout
        next_nudge = 0.0
        while not cond():
            now = time.monotonic()
            if now >= deadline:
                raise ResizeStuck(phase, version, laggards())
            if now >= next_nudge:
                # gossip converges on its own via the status exchange;
                # the nudge just shortens the tail (and costs nothing
                # when everyone already agrees)
                await self.lm.broadcast()
                next_nudge = now + max(self.poll_s * 10, 0.5)
            await asyncio.sleep(self.poll_s)
