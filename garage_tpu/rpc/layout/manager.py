"""LayoutManager: persistence, gossip merge, broadcast, pull sync.

Ref parity: src/rpc/layout/manager.rs:21-381. Owns the LayoutHistory
CRDT: merges advertisements from peers (re-broadcasting on change),
serves pulls, persists every change, and exposes the LayoutHelper to
the table/block layers.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Optional

from ...utils.background import spawn
from ...utils.migrate import decode as migrate_decode, encode as migrate_encode
from ...utils.persister import Persister
from ..replication_mode import ReplicationMode
from .helper import LayoutHelper
from .history import LayoutHistory

log = logging.getLogger("garage_tpu.rpc.layout")


class LayoutManager:
    def __init__(
        self,
        netapp,
        meta_dir: str,
        replication: ReplicationMode,
    ):
        self.netapp = netapp
        self.replication = replication
        self.persister: Persister = Persister(meta_dir, "cluster_layout", LayoutHistory)
        history = self.persister.load()
        if history is None:
            history = LayoutHistory.new(replication.factor)
        elif history.replication_factor != replication.factor:
            raise RuntimeError(
                f"persisted layout has replication_factor "
                f"{history.replication_factor}, config says {replication.factor}"
            )
        self.helper = LayoutHelper(history, netapp.id)
        self.ep = netapp.endpoint("garage_rpc/layout").set_handler(self._handle)
        self.on_change: list[Callable[[], None]] = []  # table syncers hook in

    @property
    def history(self) -> LayoutHistory:
        return self.helper.history

    def digest(self) -> bytes:
        return self.history.digest()

    # ---- local updates -------------------------------------------------

    def save(self) -> None:
        self.persister.save(self.history)

    def _changed(self) -> None:
        self.save()
        for cb in self.on_change:
            try:
                cb()
            except Exception:
                log.exception("layout on_change callback failed")
        spawn(self.broadcast(), "layout-broadcast")

    def merge_remote(self, raw: bytes) -> bool:
        remote = migrate_decode(LayoutHistory, raw)
        changed = self.history.merge(remote)
        # seeing a newer version may allow our own trackers to move
        self.helper.advance_ack()
        self.helper.advance_sync_ack()
        if changed:
            self._changed()
        return changed

    def apply_staged(self, version: Optional[int] = None) -> None:
        self.history.apply_staged_changes(version)
        self.helper.advance_ack()
        self._changed()

    def revert_staged(self) -> None:
        self.history.revert_staged_changes()
        self._changed()

    def sync_table_until(self, version: int) -> None:
        """Called by syncers when all data for layout `version` is in
        place locally (ref: manager.rs:120-133)."""
        if self.helper.sync_until(version):
            self.helper.advance_sync_ack()
            if self.history.cleanup_old_versions():
                pass
            self._changed()

    # ---- gossip --------------------------------------------------------

    async def broadcast(self) -> None:
        raw = migrate_encode(self.history)
        peers = [p for p in self.netapp.conns.keys()]
        await asyncio.gather(
            *(self._advertise_one(p, raw) for p in peers), return_exceptions=True
        )

    async def _advertise_one(self, node: bytes, raw: bytes) -> None:
        try:
            await self.ep.call(node, {"op": "advertise", "layout": raw}, 0x20, timeout=10.0)
        except Exception as e:
            log.debug("layout advertise to %s failed: %s", node[:4].hex(), e)

    async def pull_from(self, node: bytes) -> bool:
        try:
            resp, _ = await self.ep.call(node, {"op": "pull"}, 0x20, timeout=10.0)
            if resp and resp.get("layout"):
                return self.merge_remote(resp["layout"])
        except Exception as e:
            log.debug("layout pull from %s failed: %s", node[:4].hex(), e)
        return False

    async def _handle(self, from_node, payload, stream):
        op = payload.get("op")
        if op == "pull":
            return {"layout": migrate_encode(self.history)}
        if op == "advertise":
            changed = self.merge_remote(payload["layout"])
            return {"changed": changed}
        raise ValueError(f"unknown layout op {op}")
