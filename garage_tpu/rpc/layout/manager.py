"""LayoutManager: persistence, gossip merge, broadcast, pull sync.

Ref parity: src/rpc/layout/manager.rs:21-381. Owns the LayoutHistory
CRDT: merges advertisements from peers (re-broadcasting on change),
serves pulls, persists every change, and exposes the LayoutHelper to
the table/block layers.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

from ...utils.background import spawn
from ...utils.migrate import decode as migrate_decode, encode as migrate_encode
from ...utils.persister import Persister
from ..replication_mode import ReplicationMode
from .helper import LayoutHelper
from .history import LayoutHistory

log = logging.getLogger("garage_tpu.rpc.layout")


class LayoutManager:
    def __init__(
        self,
        netapp,
        meta_dir: str,
        replication: ReplicationMode,
    ):
        self.netapp = netapp
        self.replication = replication
        self.persister: Persister = Persister(meta_dir, "cluster_layout", LayoutHistory)
        history = self.persister.load()
        if history is None:
            history = LayoutHistory.new(replication.factor)
        elif history.replication_factor != replication.factor:
            raise RuntimeError(
                f"persisted layout has replication_factor "
                f"{history.replication_factor}, config says {replication.factor}"
            )
        self.helper = LayoutHelper(history, netapp.id)
        self.ep = netapp.endpoint("garage_rpc/layout").set_handler(self._handle)
        self.on_change: list[Callable[[], None]] = []  # table syncers hook in
        # layout-versioned data layers (each table's syncer, the block
        # store) register here; the node's sync tracker advances to the
        # MINIMUM across sources — see register_sync_source
        self._sync_done: dict[str, int] = {}
        # broadcast debounce: during a transition every tracker tick
        # fires _changed, and an immediate full-history broadcast to
        # every peer per tick is an O(N^2) gossip storm on big
        # clusters — coalesce to at most one broadcast per interval
        self._bcast_interval = 0.1  # `[rpc] layout_debounce_ms` / 1000
        self._bcast_last = 0.0
        self._bcast_scheduled = False

    def set_broadcast_debounce(self, seconds: float) -> None:
        """Operator knob `[rpc] layout_debounce_ms` (Garage wires it at
        startup): the minimum spacing between full-history gossip
        waves. Raise on big clusters, lower for test convergence."""
        self._bcast_interval = max(0.0, seconds)

    @property
    def history(self) -> LayoutHistory:
        return self.helper.history

    def digest(self) -> bytes:
        return self.history.digest()

    # ---- local updates -------------------------------------------------

    def save(self) -> None:
        self.persister.save(self.history)

    def _changed(self) -> None:
        self.save()
        for cb in self.on_change:
            try:
                cb()
            except Exception:
                log.exception("layout on_change callback failed")
        spawn(self._broadcast_soon(), "layout-broadcast")

    async def _broadcast_soon(self) -> None:
        """Coalescing broadcast: back-to-back tracker changes ride one
        gossip wave instead of one full-history fan-out each."""
        if self._bcast_scheduled:
            return  # an in-flight wave will carry this change too
        self._bcast_scheduled = True
        try:
            wait = self._bcast_last + self._bcast_interval \
                - time.monotonic()
            if wait > 0:
                await asyncio.sleep(wait)
            # lint: ignore[GL12] _bcast_scheduled (checked on entry) admits at most one wave; the sleeping wave is the only writer of _bcast_last
            self._bcast_last = time.monotonic()
        finally:
            self._bcast_scheduled = False
        await self.broadcast()

    def merge_remote(self, raw: bytes) -> bool:
        remote = migrate_decode(LayoutHistory, raw)
        changed = self.history.merge(remote)
        # seeing a newer version may allow our own trackers to move
        self.helper.advance_ack()
        self.helper.advance_sync_ack()
        if changed:
            self._changed()
        return changed

    def apply_staged(self, version: Optional[int] = None) -> None:
        self.history.apply_staged_changes(version)
        self.helper.advance_ack()
        self._changed()

    async def apply_staged_async(self, version: Optional[int] = None) -> None:
        """apply_staged with the assignment computed in a worker
        thread: the max-flow + movement-minimization step is pure-CPU
        and can take SECONDS on an unlucky graph — a cluster resize
        must never freeze the event loop that is serving traffic (the
        whole point of a zero-downtime transition)."""
        staged = self.history.staging
        lv = await asyncio.to_thread(self.history.compute_staged_changes,
                                     version, staged)
        # install on the loop: a concurrent layout VERSION change while
        # the compute ran is rejected by install_version, and staging
        # mutated mid-compute is preserved (not cleared) for the next
        # apply
        self.history.install_version(lv, consumed=staged)
        if len(self.history.staging.roles):
            log.warning("layout roles were staged while the v%d "
                        "assignment computed; they remain staged — "
                        "run apply again to activate them", lv.version)
        self.helper.advance_ack()
        self._changed()

    def revert_staged(self) -> None:
        self.history.revert_staged_changes()
        self._changed()

    def register_sync_source(self, name: str) -> None:
        """A layer holding layout-versioned data (one per table syncer,
        one for the block store) registers here. The node's gossiped
        sync tracker then advances to the MINIMUM completed version
        across all sources — before this, any single table finishing
        its round advanced the tracker for the whole node, and the
        cluster could GC a layout version whose other layers were
        still migrating off it."""
        self._sync_done.setdefault(name, 0)

    def sync_until_from(self, name: str, version: int) -> None:
        """Source `name` has all its data for layout `version` in
        place locally; advance the node tracker as far as the slowest
        registered source allows."""
        if version > self._sync_done.get(name, 0):
            self._sync_done[name] = version
        self._report_sync(min(self._sync_done.values()))

    def sources_synced_through(self, version: int,
                               exclude: str = "") -> bool:
        """Whether every registered sync source other than `exclude`
        has reported `version` (vacuously true with none registered).
        The block layer gates its own report on this (resync.py
        maybe_report_synced): a block_ref row that lands AFTER blocks
        reported — but before its table's round finished — would
        otherwise be unprotected by the tracker, so blocks reporting
        before the tables is exactly the premature-report hazard."""
        return all(v >= version for name, v in self._sync_done.items()
                   if name != exclude)

    def sync_table_until(self, version: int) -> None:
        """Un-sourced report — single-layer deployments and tests that
        drive the tracker directly (ref: manager.rs:120-133)."""
        self._report_sync(version)

    def _report_sync(self, version: int) -> None:
        if self.helper.sync_until(version):
            self.helper.advance_sync_ack()
            if self.history.cleanup_old_versions():
                pass
            self._changed()

    # ---- gossip --------------------------------------------------------

    async def broadcast(self) -> None:
        raw = migrate_encode(self.history)
        peers = [p for p in self.netapp.conns.keys()]
        await asyncio.gather(
            *(self._advertise_one(p, raw) for p in peers), return_exceptions=True
        )

    async def _advertise_one(self, node: bytes, raw: bytes) -> None:
        try:
            await self.ep.call(node, {"op": "advertise", "layout": raw}, 0x20, timeout=10.0)
        except Exception as e:
            log.debug("layout advertise to %s failed: %s", node[:4].hex(), e)

    async def pull_from(self, node: bytes) -> bool:
        try:
            resp, _ = await self.ep.call(node, {"op": "pull"}, 0x20, timeout=10.0)
            if resp and resp.get("layout"):
                return self.merge_remote(resp["layout"])
        except Exception as e:
            log.debug("layout pull from %s failed: %s", node[:4].hex(), e)
        return False

    async def _handle(self, from_node, payload, stream):
        op = payload.get("op")
        if op == "pull":
            return {"layout": migrate_encode(self.history)}
        if op == "advertise":
            changed = self.merge_remote(payload["layout"])
            return {"changed": changed}
        raise ValueError(f"unknown layout op {op}")
