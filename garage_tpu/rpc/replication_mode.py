"""Replication modes and quorums — the plugin boundary for erasure coding.

Ref parity: src/rpc/replication_mode.rs:8-94 (ReplicationFactor,
ConsistencyMode, quorum arithmetic). The reference only replicates whole
blocks N ways; this framework adds `erasure(k, m)` as a first-class mode
at the same boundary (the north star, BASELINE.md): metadata still
replicates n ways with the same quorums, while block *data* is striped
k+m ways with RS coding on TPU.

Quorum arithmetic:
  replicate-n consistent:  R = ceil((n+1)/2), W = n+1-R  (R+W > n)
  degraded: R = 1 (reads may miss recent writes); dangerous: R = W = 1
  erasure(k, m): a block read needs any k of n=k+m shards; a write is
  durable against the same failures as replicate-(m+1) once k+m shards
  land, but is *decodable* after any k — write quorum k+q_extra, where
  q_extra = ceil((m+1)/2) keeps read-your-writes through m failures.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum


class ConsistencyMode(Enum):
    CONSISTENT = "consistent"
    DEGRADED = "degraded"
    DANGEROUS = "dangerous"

    @classmethod
    def parse(cls, s: str) -> "ConsistencyMode":
        return cls(s.lower())


@dataclass(frozen=True)
class ReplicationMode:
    """replication_factor for metadata; optional (k, m) erasure scheme
    for block data."""

    factor: int
    consistency: ConsistencyMode = ConsistencyMode.CONSISTENT
    erasure: tuple[int, int] | None = None  # (k, m) or None = replicate

    @classmethod
    def parse(cls, replication_factor: int, consistency_mode: str = "consistent",
              erasure: str | None = None) -> "ReplicationMode":
        """erasure: "k,m" string from config, e.g. "4,2" or "10,4"."""
        scheme = None
        if erasure:
            k, m = (int(x) for x in str(erasure).replace("+", ",").split(","))
            if k < 1 or m < 1:
                raise ValueError(f"invalid erasure scheme ({k},{m})")
            scheme = (k, m)
        if replication_factor < 1:
            raise ValueError(f"invalid replication factor {replication_factor}")
        return cls(replication_factor, ConsistencyMode.parse(consistency_mode), scheme)

    # ---- metadata quorums (ref: replication_mode.rs:45-59) -------------

    @property
    def read_quorum(self) -> int:
        if self.consistency == ConsistencyMode.CONSISTENT:
            return self.factor // 2 + 1
        return 1

    @property
    def write_quorum(self) -> int:
        # Always derived from the CONSISTENT read quorum so that degraded
        # mode (R=1) relaxes reads without inflating the write quorum
        # (ref: replication_mode.rs:52-58 uses read_quorum(Consistent)).
        if self.consistency == ConsistencyMode.DANGEROUS:
            return 1
        return self.factor + 1 - (self.factor // 2 + 1)

    # ---- block data path ----------------------------------------------

    @property
    def storage_width(self) -> int:
        """Distinct nodes each block (or its shards) lands on."""
        if self.erasure is not None:
            return self.erasure[0] + self.erasure[1]
        return self.factor

    @property
    def block_write_quorum(self) -> int:
        if self.erasure is None:
            return self.write_quorum
        k, m = self.erasure
        if self.consistency == ConsistencyMode.DANGEROUS:
            return k
        return min(k + (m + 1) // 2, k + m)

    @property
    def block_read_need(self) -> int:
        """Shards needed to reconstruct (1 whole copy if replicated)."""
        return self.erasure[0] if self.erasure is not None else 1
