"""Async-hygiene rules: GL01 blocking-call-in-async, GL04 orphan-task,
GL05 swallowed-exception, GL06 await-holding-lock.

All four are single-file syntactic checks. GL01's escape hatch is the
codebase's own idiom: wrap the blocking work in a sync function (def /
lambda / method) and run it via `asyncio.to_thread` — the walker's
scope stack makes that exemption automatic, because the blocking call
then sits in a sync frame, not directly in the `async def`.
"""

from __future__ import annotations

import ast
import re

from .core import FileContext, Rule, call_name, chain_segments, dotted_name

# ---- GL01 --------------------------------------------------------------

# call targets that block the event loop outright
BLOCKING_CALLS = {
    "open",
    "time.sleep",
    "socket.socket", "socket.create_connection",
    "socket.getaddrinfo", "socket.gethostbyname",
    "sqlite3.connect",
    "subprocess.run", "subprocess.check_output", "subprocess.check_call",
    "subprocess.call", "subprocess.Popen",
    "os.system",
    "urllib.request.urlopen",
    "shutil.copyfile", "shutil.copytree", "shutil.rmtree",
}
# digest constructors/helpers: blocking only when fed real data — a
# zero-arg or constant-literal construction is instantaneous
HASH_CALLS = {
    "hashlib.md5", "hashlib.sha1", "hashlib.sha224", "hashlib.sha256",
    "hashlib.sha384", "hashlib.sha512", "hashlib.blake2b",
    "hashlib.blake2s", "hashlib.new",
    # the project's own digest helpers (utils/data.py)
    "sha256sum", "blake2sum", "blake3sum", "content_hash",
    "content_hash_matches",
}


class BlockingCallInAsync(Rule):
    id = "GL01"
    name = "blocking-call-in-async"
    summary = ("blocking I/O or digest-of-data directly inside an "
               "`async def` — the PR 2 regression class; move it off "
               "the loop with asyncio.to_thread")
    rationale = (
        "One blocking call on the event loop stalls EVERY in-flight "
        "request, not just its own — the PR 2 fast-path work moved "
        "SigV4 hashing, sqlite and file I/O into worker threads, and "
        "this rule keeps them there. The escape hatch is the "
        "codebase's own idiom: wrap the work in a sync def and run it "
        "via asyncio.to_thread (the sync frame is automatically "
        "exempt). GL10 covers the same atoms one or more helpers "
        "down the call graph.")
    example_fire = ("async def handler(req):\n"
                    "    time.sleep(0.1)            # stalls the loop")
    example_ok = ("async def handler(req):\n"
                  "    await asyncio.to_thread(time.sleep, 0.1)")

    def on_call(self, node: ast.Call, ctx: FileContext) -> None:
        if not ctx.in_async_def:
            return
        target = dotted_name(node.func)
        if target in BLOCKING_CALLS:
            ctx.report(self.id, node,
                       f"blocking call `{target}(...)` on the event "
                       "loop; wrap in asyncio.to_thread")
            return
        if target in HASH_CALLS and self._feeds_data(node):
            ctx.report(self.id, node,
                       f"digest `{target}(...)` of non-constant data "
                       "on the event loop; hash in a worker thread "
                       "(asyncio.to_thread)")

    @staticmethod
    def _feeds_data(node: ast.Call) -> bool:
        return any(not isinstance(a, ast.Constant) for a in node.args)


# ---- GL04 --------------------------------------------------------------

SPAWN_CALLS = {"create_task", "ensure_future"}


class OrphanTask(Rule):
    id = "GL04"
    name = "orphan-task"
    summary = ("asyncio.create_task/ensure_future result dropped — an "
               "un-retained task can be garbage-collected mid-flight "
               "and its exception is never observed; store it, await "
               "it, or add_done_callback")
    rationale = (
        "CPython keeps only a weak reference to scheduled tasks: a "
        "dropped create_task result can be garbage-collected MID-"
        "FLIGHT, and its exception is silently lost either way. PR 5 "
        "converted 8 such sites to utils.background.spawn (retained "
        "until done, exception logged). Runs on harness files too — "
        "an orphaned task in clusterbox corrupts chaos-soak verdicts.")
    example_fire = "asyncio.create_task(self._flush())   # dropped"
    example_ok = "self._task = spawn(self._flush(), 'flush')"

    def on_expr_stmt(self, node: ast.Expr, ctx: FileContext) -> None:
        call = node.value
        if not isinstance(call, ast.Call):
            return
        name = call_name(call)
        if name not in SPAWN_CALLS:
            return
        segs = chain_segments(call.func)
        # create_task must come from asyncio / a loop, not an arbitrary
        # object's create_task method... but any `.create_task(` drop
        # is suspicious enough to flag; waive the exceptions.
        ctx.report(self.id, node,
                   f"`{'.'.join(segs)}(...)` result dropped; retain "
                   "the task (store + add_done_callback) or await it")


# ---- GL05 --------------------------------------------------------------

def _is_swallow_body(body: list[ast.stmt]) -> bool:
    """True when the handler body does nothing: only pass / continue /
    `return` / `return None` (docstring-free — any call, log, counter
    or attribute write makes it a real handler)."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Return):
            if stmt.value is None or (isinstance(stmt.value, ast.Constant)
                                      and stmt.value.value is None):
                continue
            return False
        return False
    return True


class SwallowedException(Rule):
    id = "GL05"
    name = "swallowed-exception"
    summary = ("`except Exception`/bare `except` whose body only "
               "passes/continues/returns None — the Aspirator check "
               "(Yuan et al., OSDI '14); log and count it, or waive "
               "with the reason the swallow is safe")
    rationale = (
        "Yuan et al. (OSDI '14) traced the majority of catastrophic "
        "distributed-storage failures to exactly these do-nothing "
        "handlers — the failure was DETECTED and then discarded. Log "
        "it, count it, or waive it with the reason the swallow is "
        "provably safe. Runs on harness files too: a swallowed "
        "exception in the workload driver turns a real failure into "
        "a passing soak.")
    example_fire = ("try:\n    push(peer)\nexcept Exception:\n"
                    "    pass                    # failure discarded")
    example_ok = ("try:\n    push(peer)\nexcept Exception as e:\n"
                  "    log.debug('push to %s failed: %s', peer, e)")

    def on_except(self, node: ast.ExceptHandler, ctx: FileContext) -> None:
        t = node.type
        if t is None:
            kind = "bare except"
        elif isinstance(t, ast.Name) and t.id in ("Exception",
                                                  "BaseException"):
            kind = f"except {t.id}"
        else:
            return
        if _is_swallow_body(node.body):
            ctx.report(self.id, node,
                       f"{kind}: exception silently swallowed "
                       "(body is only pass/continue/return None)")


# ---- GL06 --------------------------------------------------------------

RPC_METHODS = {"try_call_many", "try_write_many_sets",
               "rpc_get_block", "rpc_put_block"}
RPC_RECEIVERS = {"rpc", "ep", "endpoint", "rpc_helper"}
GL06_DIRS = re.compile(r"(^|/)(table|block)/")


class AwaitHoldingLock(Rule):
    id = "GL06"
    name = "await-holding-lock"
    summary = ("awaiting a network/RPC call inside a `with <lock>:` / "
               "`async with <lock>:` body in table/ or block/ — the "
               "lock is held across the whole remote round-trip and "
               "serializes every other waiter behind a peer's tail "
               "latency (sync threading locks count since ISSUE 9: "
               "they stall the WHOLE loop, not just one task)")
    rationale = (
        "A lock held across a network await couples local concurrency "
        "to a PEER's tail latency: one slow replica and every other "
        "task queues behind the lock for seconds. Since ISSUE 9 sync "
        "`with lock():` frames count too. Deliberate holds (e.g. the "
        "layout write_lock, which is a version PIN, not mutual "
        "exclusion) carry reasoned waivers.")
    example_fire = ("async with self._lock:\n"
                    "    await self.rpc.try_call_many(...)")
    example_ok = ("async with self._lock:\n    payload = build()\n"
                  "await self.rpc.try_call_many(...)")

    def applies_to(self, ctx: FileContext) -> bool:
        return (not ctx.is_test) and bool(GL06_DIRS.search(ctx.rel_path))

    def on_await(self, node: ast.Await, ctx: FileContext) -> None:
        if not ctx.lock_stack:
            return
        call = node.value
        if not isinstance(call, ast.Call):
            return
        segs = chain_segments(call.func)
        if not segs:
            return
        is_rpc = (segs[-1] in RPC_METHODS
                  or (segs[-1] == "call"
                      and any(s in RPC_RECEIVERS for s in segs[:-1]))
                  or any(s in ("rpc", "rpc_helper") for s in segs[:-1]))
        if is_rpc:
            ctx.report(self.id, node,
                       f"RPC `{'.'.join(segs)}` awaited while holding "
                       "an async lock; release the lock before the "
                       "network round-trip")
