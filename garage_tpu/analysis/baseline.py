"""Committed baseline for grandfathered violations.

The baseline is the escape valve that lets the lint gate land strict
from day one: pre-existing violations that are deliberate (and carry
too much context for an inline waiver) are enumerated in a committed
JSON file; everything NOT listed fails the build. Entries match on
(rule, path, context) — context is the enclosing def/class qualname —
so ordinary line churn around a grandfathered site does not break CI,
while moving or duplicating the pattern into NEW code does.

Baseline entries rot like waivers do: an entry that matches nothing is
reported as a GL00 violation, so the file can only shrink. ISSUE 5
ships it (near-)empty — the point of the PR is fixing the findings,
not cataloguing them.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from .core import META_RULE, Violation

BASELINE_VERSION = 1
DEFAULT_BASELINE = "lint-baseline.json"


@dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str
    context: str
    reason: str = ""

    def key(self) -> tuple:
        return (self.rule, self.path, self.context)


def load_baseline(path: str) -> list[BaselineEntry]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
    except FileNotFoundError:
        return []
    entries = []
    for e in raw.get("entries", []):
        entries.append(BaselineEntry(
            rule=e["rule"], path=e["path"],
            context=e.get("context", "<module>"),
            reason=e.get("reason", "")))
    return entries


def save_baseline(path: str, violations: list[Violation]) -> int:
    """--write-baseline: snapshot every active violation. Dedupes on
    the match key (one entry covers all same-shaped sites in a
    scope)."""
    seen = set()
    entries = []
    for v in violations:
        if not v.active or v.rule == META_RULE:
            continue
        k = (v.rule, v.path, v.context)
        if k in seen:
            continue
        seen.add(k)
        entries.append({"rule": v.rule, "path": v.path,
                        "context": v.context,
                        "reason": "grandfathered; fix or justify"})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": BASELINE_VERSION, "entries": entries},
                  f, indent=2, sort_keys=True)
        f.write("\n")
    return len(entries)


def apply_baseline(violations: list[Violation],
                   entries: list[BaselineEntry]) -> list[Violation]:
    """Mark baselined violations in place; returns GL00 violations for
    entries that no longer match anything (stale suppression)."""
    used: set[tuple] = set()
    by_key: dict[tuple, BaselineEntry] = {e.key(): e for e in entries}
    for v in violations:
        if v.rule == META_RULE or v.waived:
            continue
        e = by_key.get((v.rule, v.path, v.context))
        if e is not None:
            v.baselined = True
            used.add(e.key())
    stale = []
    for e in entries:
        if e.key() in used:
            continue
        stale.append(Violation(
            rule=META_RULE, path=e.path, line=1, col=0,
            message=f"stale baseline entry {e.rule} in context "
                    f"`{e.context}`: matches nothing — remove it",
            context=e.context))
    return stale
