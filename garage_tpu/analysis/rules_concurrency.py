"""Concurrency-soundness flow rules (ISSUE 14): GL12
await-interleaving-atomicity and GL13 lock-order-inversion.

Python's cooperative scheduler makes every `await` a preemption point:
any other task can run between the statement before the await and the
statement after it. Garage's correctness story rests on single-writer
invariants that hold only while a frame does NOT yield — and PRs 8-11
multiplied the shared mutable surface that straddles awaits (lease
pool accounting, gateway rosters, feeder in-flight maps, pipeline
generation state, peer-health rings).

GL12 is the TSan-style stale-check detector, specialized to asyncio:
a read of a shared lvalue (self-attribute or module-level state — the
GL09 census), then an await with NO lock held, then a write of the
same lvalue. The classic firing shape is check-then-act::

    if h not in self._inflight:
        fut = await self._start(h)      # another task can insert h here
        self._inflight[h] = fut         # ...and this clobbers it

The await may be interprocedural: the write can live in the awaited
callee (resolved through the call graph for same-object `self.x()`
calls and same-module functions). Re-checking after the await — a read
of the lvalue between the await and the write — suppresses the
finding: that IS the fix idiom. So does holding any lock across the
await (the pass-1 locks-at-await facts), and so does the guard-loop
idiom (`while cond: await ...` re-evaluates its test before falling
through — the summary walk re-emits the test's reads after the body).

GL13 is classic lock-order-cycle detection over a GLOBAL acquisition
graph: lock identity is the resolved attribute path (`Cls._lock`,
`module._global_lock` — the name-scope machinery pass 1 already has),
an edge A -> B exists wherever B is acquired (`async with` / `with` /
`.acquire()`) while A is held — including through resolved calls — and
any cycle is the ABBA deadlock no test reliably reproduces. Both full
chains are reported with their file:line witnesses.
"""

from __future__ import annotations

from .core import ProjectState, Rule, Violation
# one home for "which files do flow rules check" — GL12/GL13 must
# never diverge from GL10/GL11 on scope policy
from .rules_dataflow import _dataflow, _is_checked_file

# call-graph expansion caps (defense against pathological graphs)
_WRITE_DEPTH = 6
_LOCK_DEPTH = 6


def _lv_str(lv: list) -> str:
    return f"self.{lv[1]}" if lv[0] == "self" else lv[1]


class AwaitInterleavingAtomicity(Rule):
    id = "GL12"
    name = "await-interleaving-atomicity"
    needs_dataflow = True
    summary = ("read -> await -> write on the same shared lvalue "
               "(self-attribute / module state) in an async frame with "
               "no lock held across the await — every await is a "
               "preemption point, so another task can invalidate the "
               "read before the write lands (check-then-act race); "
               "re-check after the await, or hold a lock across it")
    rationale = (
        "Any `await` is a preemption point: the cooperative scheduler "
        "can run EVERY other task between the read and the write, so "
        "a decision made before the await is stale by the time the "
        "write lands — the classic shape is check-then-act on an "
        "in-flight map (`if h not in self._inflight: await ...; "
        "self._inflight[h] = fut` — two concurrent callers both pass "
        "the check and the second clobbers the first's entry). The "
        "write may also live in the awaited callee (resolved through "
        "the call graph). Recognized-safe shapes: any lock held "
        "across the await (pass-1 locks-at-await facts), a RE-READ of "
        "the lvalue between the await and the write (re-validation is "
        "the fix idiom), and the guard-loop `while cond: await` "
        "(its test is re-evaluated before falling through). Since "
        "ISSUE 20 loop bodies that await are unrolled once in the "
        "event stream (the CFG back-edge), so the loop-carried race — "
        "read late in iteration i, write after the await early in "
        "iteration i+1 — fires too.")
    example_fire = ("async def start(self, h):\n"
                    "    if h not in self._inflight:\n"
                    "        fut = await self._spawn(h)\n"
                    "        self._inflight[h] = fut   # stale check")
    example_ok = ("async def start(self, h):\n"
                  "    fut = self._inflight.get(h)\n"
                  "    if fut is None:\n"
                  "        fut = await self._spawn(h)\n"
                  "        if h not in self._inflight:  # re-checked\n"
                  "            self._inflight[h] = fut")

    def finish_project(self, project: ProjectState) -> list[Violation]:
        df = _dataflow(project)
        if df is None:
            return []
        g = df.graph
        out: list[Violation] = []
        file_ok: dict[str, bool] = {}
        for fid in sorted(g.functions):
            fn = g.functions[fid]
            if not fn["is_async"] or not fn.get("accesses"):
                continue
            path = fn["path"]
            if path not in file_ok:
                file_ok[path] = _is_checked_file(project, path)
            if not file_ok[path]:
                continue
            out.extend(self._check_function(g, fid, fn))
        return out

    def _check_function(self, g, fid: str, fn: dict) -> list[Violation]:
        # open: lv-key -> read line (no await crossed yet);
        # pending: lv-key -> (read line, await line) — a lock-free
        # await separates the read from any later write
        open_reads: dict[tuple, int] = {}
        pending: dict[tuple, tuple[int, int]] = {}
        fired: set[tuple] = set()
        out: list[Violation] = []

        def fire(lv, read_ln, await_ln, write_ln, where=""):
            key = tuple(lv)
            if key in fired:
                return
            fired.add(key)
            name = _lv_str(lv)
            out.append(Violation(
                rule=self.id, path=fn["path"], line=write_ln, col=0,
                message=(
                    f"`{name}` read at line {read_ln}, then awaited at "
                    f"line {await_ln} with no lock held, then written "
                    f"{where}— the await is a preemption point, so the "
                    f"line-{read_ln} check is stale when the write "
                    "lands (check-then-act race); re-check after the "
                    "await or hold a lock across it"),
                context=fn["qualname"]))

        for ev in fn["accesses"]:
            k = ev["k"]
            if k == "x":
                # return barrier: flows that crossed the await left
                # the frame here; later writes sit on no-await paths
                pending.clear()
                continue
            if k == "r":
                key = tuple(ev["lv"])
                # a re-read AFTER an await re-validates the state:
                # that is the fix idiom, so it clears the pending pair
                pending.pop(key, None)
                open_reads[key] = ev["line"]
            elif k == "w":
                key = tuple(ev["lv"])
                if key in pending:
                    r_ln, a_ln = pending.pop(key)
                    if not ev.get("flag"):
                        fire(ev["lv"], r_ln, a_ln, ev["line"])
                open_reads.pop(key, None)
            elif k == "a":
                if ev["locks"]:
                    continue  # lock held across the await: atomic
                if ev.get("ret"):
                    continue  # control leaves the frame at this await
                for key, r_ln in list(open_reads.items()):
                    pending[key] = (r_ln, ev["line"])
                open_reads.clear()
                # interprocedural: the awaited callee (chain) writes
                # the lvalue this frame just checked
                if ev.get("call"):
                    self._callee_write_check(
                        g, fid, ev, pending, fire)
            elif k == "c":
                # a sync self-call can carry the write (`await x();
                # self._store(h)` where _store writes the map)
                if not ev.get("call"):
                    continue
                for lv, w_fid, flag in self._callee_writes(
                        g, fid, ev["call"]):
                    key = tuple(lv)
                    if key in pending:
                        r_ln, a_ln = pending.pop(key)
                        if not flag:
                            w_fn = g.functions[w_fid]
                            fire(lv, r_ln, a_ln, ev["line"],
                                 where=f"in `{w_fn['qualname']}` "
                                       f"(called at line {ev['line']}) ")
                    open_reads.pop(key, None)
        return out

    def _callee_write_check(self, g, fid, ev, pending, fire):
        for lv, w_fid, flag in self._callee_writes(g, fid, ev["call"]):
            key = tuple(lv)
            if key in pending:
                r_ln, a_ln = pending.pop(key)
                if not flag:
                    w_fn = g.functions[w_fid]
                    fire(lv, r_ln, a_ln, ev["line"],
                         where=f"in awaited `{w_fn['qualname']}` ")

    def _callee_writes(self, g, caller_id: str, ref: list):
        """(lvalue, writer fid, benign) triples the callee chain
        writes, with same-object guarantees: `self.x` lvalues
        propagate only through `self.m()` refs (same instance),
        module-state lvalues only within the same module. Accretive
        writes and writes the callee re-validates (a read of the same
        lvalue immediately before, no await between) are skipped —
        they act on live state, not on the caller's stale check."""
        callee = g.resolve_ref(caller_id, ref)
        if callee is None:
            return
        same_self = ref[0] == "self"
        caller_mod = caller_id.split(":", 1)[0]
        seen: set[str] = set()
        stack = [(callee, same_self, 0)]
        while stack:
            cur, self_ok, depth = stack.pop()
            if cur in seen or depth > _WRITE_DEPTH:
                continue
            seen.add(cur)
            cur_fn = g.functions[cur]
            cur_mod = cur.split(":", 1)[0]
            read_since_await: set[tuple] = set()
            for ev in cur_fn.get("accesses", []):
                if ev["k"] == "a":
                    read_since_await.clear()
                    continue
                if ev["k"] == "r":
                    read_since_await.add(tuple(ev["lv"]))
                    continue
                if ev["k"] != "w":
                    continue
                lv = ev["lv"]
                # a write the callee derives from a read it made after
                # its own last preemption point acts on LIVE state
                if ev.get("acc") or tuple(lv) in read_since_await:
                    continue
                flag = bool(ev.get("flag"))
                if lv[0] == "self" and self_ok:
                    yield lv, cur, flag
                elif lv[0] == "mod" and cur_mod == caller_mod:
                    yield lv, cur, flag
            for nxt, rec in g.edges_from(cur):
                if rec["via_thread"]:
                    continue
                stack.append((nxt, self_ok and rec["ref"][0] == "self",
                              depth + 1))


class LockOrderInversion(Rule):
    id = "GL13"
    name = "lock-order-inversion"
    needs_dataflow = True
    summary = ("two locks are acquired in opposite orders on different "
               "code paths (lock identity = resolved attribute path "
               "plus, since ISSUE 20, the allocation site of a local "
               "receiver — two instances of one class are distinct, "
               "two aliases of one instance are not; acquisitions seen "
               "through `async with` / `with` / `.acquire()`, "
               "including through resolved calls) — the classic ABBA "
               "deadlock; pick one global order and stick to it")
    rationale = (
        "If path 1 holds A while taking B and path 2 holds B while "
        "taking A, two tasks can each hold one lock and wait forever "
        "on the other — the ABBA deadlock that no test reliably "
        "reproduces because it needs the exact interleaving. The rule "
        "builds a GLOBAL acquisition graph (edge A -> B = B acquired "
        "while A held, lock identity = class-qualified attribute "
        "path — allocation-site-qualified for locks reached through "
        "a local constructed in the frame, so `x = Guard(); y = "
        "Guard()` yields two identities while `y = x` aliases one — "
        "edges also found THROUGH resolved calls) and reports "
        "every cycle with both witness chains. The fix is a single "
        "global acquisition order — usually: take the coarser lock "
        "first, or restructure so one lock is released before the "
        "other is taken.")
    example_fire = ("async def a(self):\n"
                    "    async with self._lock_a:\n"
                    "        async with self._lock_b: ...\n"
                    "async def b(self):\n"
                    "    async with self._lock_b:\n"
                    "        async with self._lock_a: ...")
    example_ok = ("async def a(self):\n"
                  "    async with self._lock_a:\n"
                  "        async with self._lock_b: ...\n"
                  "async def b(self):\n"
                  "    async with self._lock_a:   # same global order\n"
                  "        async with self._lock_b: ...")

    def finish_project(self, project: ProjectState) -> list[Violation]:
        df = _dataflow(project)
        if df is None:
            return []
        g = df.graph
        file_ok: dict[str, bool] = {}

        def checked(path: str) -> bool:
            if path not in file_ok:
                file_ok[path] = _is_checked_file(project, path)
            return file_ok[path]

        # edge (A, B) -> first witness {path, line, fn, note}
        edges: dict[tuple[str, str], dict] = {}

        def add_edge(a: str, b: str, fn: dict, line: int, note: str):
            if a == b:
                return  # re-entrant same-identity: not an order cycle
            edges.setdefault((a, b), {
                "path": fn["path"], "line": line,
                "fn": fn["qualname"], "note": note})

        for fid in sorted(g.functions):
            fn = g.functions[fid]
            if not checked(fn["path"]):
                continue
            for acq in fn.get("lock_acqs", []):
                b = self._qualify(fn, acq["lock"])
                for h in acq["held"]:
                    add_edge(self._qualify(fn, h), b, fn,
                             acq["line"], "")
            # through calls: a callee (chain) acquires while this
            # frame holds a lock ("c" = sync call with held locks,
            # "a" = awaited call with locks-at-await)
            for ev in fn.get("accesses", []):
                if ev["k"] not in ("c", "a") or not ev.get("call"):
                    continue
                held = ev.get("held") or ev.get("locks") or []
                if not held:
                    continue
                for lock, where in self._callee_locks(g, fid,
                                                      ev["call"]):
                    for h in held:
                        add_edge(self._qualify(fn, h), lock, fn,
                                 ev["line"], f" via {where}")

        return self._report_cycles(edges)

    def _qualify(self, fn: dict, lock: str) -> str:
        """Class-qualify self-rooted lock paths, module-qualify the
        rest — the identity two functions must agree on for an edge
        to connect them."""
        if lock.startswith("self.") or lock.startswith("cls."):
            rest = lock.split(".", 1)[1]
            cls = fn.get("class") or fn["qualname"]
            return f"{fn['module']}.{cls}.{rest}"
        return f"{fn['module']}.{lock}"

    def _callee_locks(self, g, caller_id: str, ref: list):
        """(qualified lock, holder qualname) for every lock the callee
        chain acquires."""
        callee = g.resolve_ref(caller_id, ref)
        if callee is None:
            return
        seen: set[str] = set()
        stack = [(callee, 0)]
        while stack:
            cur, depth = stack.pop()
            if cur in seen or depth > _LOCK_DEPTH:
                continue
            seen.add(cur)
            cur_fn = g.functions[cur]
            for acq in cur_fn.get("lock_acqs", []):
                yield (self._qualify(cur_fn, acq["lock"]),
                       cur_fn["qualname"])
            for nxt, rec in g.edges_from(cur):
                if not rec["via_thread"]:
                    stack.append((nxt, depth + 1))

    def _report_cycles(self, edges: dict) -> list[Violation]:
        graph: dict[str, list[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, []).append(b)
            graph.setdefault(b, [])
        for k in graph:
            graph[k].sort()

        out: list[Violation] = []
        reported: set[frozenset] = set()
        # DFS from each node (sorted: deterministic) finding one cycle
        # per distinct lock set
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in graph.get(node, []):
                    if nxt == start and len(path) >= 2:
                        key = frozenset(path)
                        if key in reported:
                            continue
                        reported.add(key)
                        out.append(self._cycle_violation(path, edges))
                    elif nxt not in path and len(path) < 6:
                        stack.append((nxt, path + [nxt]))
        return out

    def _cycle_violation(self, path: list[str],
                         edges: dict) -> Violation:
        hops = []
        for i, a in enumerate(path):
            b = path[(i + 1) % len(path)]
            w = edges[(a, b)]
            hops.append(f"{a} -> {b} at {w['path']}:{w['line']} "
                        f"in {w['fn']}{w['note']}")
        w0 = edges[(path[0], path[1 % len(path)])]
        return Violation(
            rule=self.id, path=w0["path"], line=w0["line"], col=0,
            message=("lock-order cycle (ABBA deadlock): "
                     + "; ".join(hops)
                     + " — pick one global acquisition order"),
            context=w0["fn"])
