"""Best-effort project call graph over pass-1 summaries (ISSUE 9).

Resolution is deliberately conservative — an edge exists only when the
target is nameable with high confidence:

  * bare names: the caller's own nested defs (walking up the enclosing-
    function chain), then module-level functions, then imports that
    land on a project function;
  * `self.m` / `cls.m`: the enclosing class's methods, then base
    classes resolvable in-project (depth-limited, cycle-tolerant);
  * `mod.func` dotted chains rooted at an imported module;
  * `obj.m` on an arbitrary receiver: receiver TYPE facts from pass 1
    (parameter annotations, `x = Cls(...)` constructor assignments,
    `isinstance` guards — import-aware, ISSUE 20) rank first: a typed
    receiver resolves through its class, and a receiver typed to an
    out-of-project import contributes NO edge even when CHA would have
    guessed one (the `ET.Element.iter` vs `db.Tree.iter` fix); only
    untyped receivers fall back to class-hierarchy analysis, and ONLY
    when exactly one project class defines `m` (unique-method CHA) —
    common names like `get` disqualify themselves by ubiquity;
  * `asyncio.to_thread(f, ...)`, `loop.run_in_executor(_, f, ...)` and
    `functools.partial(f, ...)` were unwrapped in pass 1: the edge
    targets `f`, flagged `via_thread` for the executor hops so the
    blocking rule knows the frame left the loop.

Unresolved calls simply contribute no edge: the flow rules under-report
rather than guess. Reachability queries are iterative with a visited
set, so call cycles (A -> B -> A) terminate.
"""

from __future__ import annotations

from typing import Iterator, Optional

# self.m resolution climbs at most this many base-class links
_BASE_DEPTH = 4

# tri-state marker for typed-receiver resolution: "no type fact" (fall
# back to CHA), distinct from None ("typed: definitely no project edge")
_UNKNOWN = object()


class CallGraph:
    def __init__(self, file_summaries: dict[str, dict]):
        """file_summaries: rel_path -> summarize_tree() product."""
        self.files = file_summaries
        # module -> file summary
        self.modules: dict[str, dict] = {}
        # global function id "module:qualname" -> function summary
        self.functions: dict[str, dict] = {}
        # method name -> sorted list of function ids (for unique CHA)
        self._methods: dict[str, list[str]] = {}
        # function id -> list of (callee id, call record)
        self._edges: dict[str, list[tuple[str, dict]]] = {}

        # functions that are blocking-by-annotation: @blocking_api on
        # the def, or `blocking_api = True` on the enclosing class —
        # the exact replacement for GL10's db-receiver-name heuristic
        # wherever the call resolves in-project (ISSUE 14 satellite)
        self._annotated: set[str] = set()
        # top-level package names of the project itself — an import
        # whose target leaves this set types its receiver as external
        self._project_roots: set[str] = {
            fs["module"].split(".")[0] for fs in file_summaries.values()}

        for fs in file_summaries.values():
            self.modules[fs["module"]] = fs
            for qn, fn in fs["functions"].items():
                fid = f"{fs['module']}:{qn}"
                self.functions[fid] = fn
                cls = fs["classes"].get(fn.get("class") or "")
                if fn.get("blocking_api") \
                        or (cls is not None and cls.get("blocking_api")):
                    self._annotated.add(fid)
        for fs in file_summaries.values():
            for cname, cls in fs["classes"].items():
                for m, mq in cls["methods"].items():
                    self._methods.setdefault(m, []).append(
                        f"{fs['module']}:{mq}")
        for m in self._methods:
            self._methods[m].sort()
        for fid, fn in self.functions.items():
            self._edges[fid] = []
            for rec in fn["calls"]:
                callee = self.resolve(fid, rec)
                if callee is not None and callee in self.functions:
                    self._edges[fid].append((callee, rec))

    # ---- resolution -----------------------------------------------------

    def resolve(self, caller_id: str, rec: dict) -> Optional[str]:
        module, qualname = caller_id.split(":", 1)
        fs = self.modules.get(module)
        if fs is None:
            return None
        kind, target = rec["ref"][0], rec["ref"][1]
        if kind == "name":
            return self._resolve_name(fs, qualname, target)
        if kind == "self":
            fn = self.functions.get(caller_id)
            cls = fn.get("class") if fn else None
            if cls:
                hit = self._resolve_method(fs, cls, target, 0, set())
                if hit:
                    return hit
            # fall back to unique-method CHA: covers a base class
            # calling a method only its (single) subclass defines
            hits = self._methods.get(target, [])
            return hits[0] if len(hits) == 1 else None
        if kind == "dotted":
            hit = self._resolve_dotted(fs, target)
            if hit:
                return hit
            # not an import-rooted chain: treat the last segment as a
            # method receiver and fall back to unique-method CHA
            target = target.rsplit(".", 1)[-1]
            kind = "attr"
        if kind == "attr":
            typed = self._resolve_typed(caller_id, fs, rec, target)
            if typed is not _UNKNOWN:
                return typed
            hits = self._methods.get(target, [])
            if len(hits) == 1:
                return hits[0]
            return None
        return None

    def _resolve_typed(self, caller_id: str, fs: dict, rec: dict,
                       method: str):
        """Import-aware receiver typing (ISSUE 20). When pass 1 learned
        the single-name receiver's type (parameter annotation,
        constructor assignment, isinstance guard), that fact outranks
        unique-method CHA: an in-project class resolves through
        `_resolve_method` (None when the method is absent there), and a
        receiver typed by an import that leaves the project is external
        — no project edge, no CHA guess. Returns a function id, None
        (authoritative negative), or _UNKNOWN (no usable type fact)."""
        recv = rec.get("recv") or []
        if len(recv) != 1 or recv[0] in ("self", "cls"):
            return _UNKNOWN
        fn = self.functions.get(caller_id)
        if fn is None:
            return _UNKNOWN
        vt = (fn.get("var_types") or {}).get(recv[0])
        if not vt:
            return _UNKNOWN
        chain = vt["t"].split(".")
        cls_fs, cls_name = self._class_of_chain(fs, chain)
        if cls_name is not None:
            return self._resolve_method(cls_fs, cls_name, method, 0,
                                        set())
        imp = fs["imports"].get(chain[0])
        if imp is not None \
                and imp.split(".")[0] not in self._project_roots:
            return None
        return _UNKNOWN

    def _class_of_chain(self, fs: dict, chain: list):
        """(file_summary, class_name) when a type chain names an
        in-project class — same-module by bare name, or through this
        module's imports ("mod.Cls", an aliased class, a re-export) —
        else (None, None)."""
        if len(chain) == 1 and chain[0] in fs["classes"]:
            return fs, chain[0]
        imp = fs["imports"].get(chain[0])
        if imp is None:
            return None, None
        dotted = imp + ("." + ".".join(chain[1:])
                        if len(chain) > 1 else "")
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            tfs = self.modules.get(mod)
            if tfs is not None:
                cls = ".".join(parts[i:])
                if cls in tfs["classes"]:
                    return tfs, cls
                return None, None
        return None, None

    def _resolve_name(self, fs: dict, caller_qn: str,
                      name: str) -> Optional[str]:
        # 1) nested defs of the caller, walking up the parent chain
        qn = caller_qn
        while qn:
            fn = fs["functions"].get(qn)
            if fn is None:
                break
            nested = fn.get("nested", {})
            if name in nested:
                return f"{fs['module']}:{nested[name]}"
            qn = fn.get("parent", "")
        # 2) module-level functions
        if name in fs["top_functions"]:
            return f"{fs['module']}:{fs['top_functions'][name]}"
        # 3) imports landing on a project function
        tgt = fs["imports"].get(name)
        if tgt:
            return self._function_id_for(tgt)
        return None

    def _resolve_dotted(self, fs: dict, dotted: str) -> Optional[str]:
        root, rest = (dotted.split(".", 1) + [""])[:2]
        base = fs["imports"].get(root)
        if base is None or not rest:
            return None
        return self._function_id_for(f"{base}.{rest}")

    def _function_id_for(self, dotted: str) -> Optional[str]:
        """'pkg.mod.func' or 'pkg.mod.Class.meth' -> function id."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            fs = self.modules.get(mod)
            if fs is None:
                continue
            qn = ".".join(parts[i:])
            if qn in fs["functions"]:
                return f"{mod}:{qn}"
            return None
        return None

    def _resolve_method(self, fs: dict, cls: str, method: str,
                        depth: int, seen: set) -> Optional[str]:
        if depth > _BASE_DEPTH or (fs["module"], cls) in seen:
            return None
        seen.add((fs["module"], cls))
        cinfo = fs["classes"].get(cls)
        if cinfo is None:
            return None
        if method in cinfo["methods"]:
            return f"{fs['module']}:{cinfo['methods'][method]}"
        for base in cinfo.get("bases", []):
            base_name = base.split(".")[-1]
            # base in the same module?
            if base_name in fs["classes"]:
                hit = self._resolve_method(fs, base_name, method,
                                           depth + 1, seen)
                if hit:
                    return hit
                continue
            # imported base?
            tgt = fs["imports"].get(base.split(".")[0])
            if tgt:
                dotted = tgt + ("." + ".".join(base.split(".")[1:])
                                if "." in base else "")
                for i in range(len(dotted.split(".")), 0, -1):
                    mod = ".".join(dotted.split(".")[:i])
                    bfs = self.modules.get(mod)
                    if bfs is not None:
                        bcls = ".".join(dotted.split(".")[i:])
                        if bcls:
                            hit = self._resolve_method(
                                bfs, bcls, method, depth + 1, seen)
                            if hit:
                                return hit
                        break
        return None

    # ---- queries --------------------------------------------------------

    def edges_from(self, fid: str) -> list[tuple[str, dict]]:
        return self._edges.get(fid, [])

    def resolve_ref(self, caller_id: str, ref: list) -> Optional[str]:
        """Resolve a bare call ref (no full record) to a function id
        known to the graph, or None."""
        callee = self.resolve(caller_id, {"ref": ref})
        return callee if callee in self.functions else None

    def is_blocking_api(self, fid: Optional[str]) -> bool:
        return fid in self._annotated

    def atoms_of(self, fid: str):
        """The function's EFFECTIVE blocking atoms (ISSUE 14):

          * hard-I/O atoms unchanged;
          * heuristic db atoms (db-named receiver + db-verb method)
            kept only when the call does NOT resolve to an in-project
            function, or resolves to a @blocking_api one — the
            annotation is authoritative wherever it can speak, the
            name heuristic covers out-of-tree callables;
          * calls (non-awaited, non-thread-hop) resolving to a
            @blocking_api function become atoms even where the
            receiver name never matched the heuristic.
        """
        fn = self.functions.get(fid)
        if fn is None:
            return
        seen_lines = set()
        for atom in fn["blocking"]:
            if atom["kind"] != "db":
                yield atom
                continue
            ref = atom.get("ref")
            callee = self.resolve_ref(fid, ref) if ref else None
            if callee is None or callee in self._annotated:
                seen_lines.add(atom["line"])
                yield atom
        for callee, rec in self.edges_from(fid):
            if callee in self._annotated and not rec["awaited"] \
                    and not rec["via_thread"] \
                    and rec["line"] not in seen_lines:
                target = self.functions[callee]
                yield {"target": target["qualname"],
                       "line": rec["line"], "kind": "api"}

    def bound_call(self, caller_id: str, rec: dict) -> bool:
        """True when the call binds its receiver as `self` — positional
        arguments then land one parameter later. self/attr refs are
        bound by construction; a "dotted" ref is bound iff it did NOT
        resolve through an imported module (i.e. it fell back to
        unique-method CHA on `obj.m`)."""
        kind = rec["ref"][0]
        if kind in ("self", "attr"):
            return True
        if kind == "dotted":
            module = caller_id.split(":", 1)[0]
            fs = self.modules.get(module)
            return not (fs is not None
                        and self._resolve_dotted(fs, rec["ref"][1]))
        return False

    def blocking_chains(self, fid: str,
                        max_depth: int = 8) -> Iterator[list]:
        """Chains [ (callee id, call record)..., blocking atom ] from
        `fid` through SYNC project frames to a blocking atom, skipping
        thread-hop edges, async callees (their own rule's business) and
        generators — EXCEPT a generator reached by ITERATION (`for x
        in gen(...)` / `async for`): iterating runs the body on this
        frame, so its atoms count, reported at the iteration site
        (ISSUE 14 satellite; plain calls stay exempt). @blocking_api
        callees are atoms themselves (atoms_of) and are not expanded.
        Cycle-tolerant: a function is expanded at most once per
        query."""
        visited = {fid}
        stack: list[tuple[str, list]] = [(fid, [])]
        while stack:
            cur, path = stack.pop()
            for callee, rec in sorted(self.edges_from(cur),
                                      key=lambda e: (e[1]["line"], e[0])):
                if rec["via_thread"]:
                    continue
                if callee in visited:
                    continue
                target = self.functions[callee]
                iterated_gen = target["is_generator"] \
                    and rec.get("iterated")
                if (target["is_async"] or target["is_generator"]) \
                        and not iterated_gen:
                    continue
                if callee in self._annotated:
                    continue  # the CALL is the atom (atoms_of)
                visited.add(callee)
                new_path = path + [(callee, rec)]
                for atom in self.atoms_of(callee):
                    yield new_path + [atom]
                if len(new_path) < max_depth:
                    stack.append((callee, new_path))

    def param_index(self, fid: str, pos: int,
                    shift_self: bool) -> Optional[str]:
        """Name of the callee parameter a positional argument lands on
        (accounting for the bound `self` when called as a method)."""
        fn = self.functions.get(fid)
        if fn is None:
            return None
        params = fn["params"]
        if shift_self and fn.get("is_method"):
            pos += 1
        return params[pos] if pos < len(params) else None
