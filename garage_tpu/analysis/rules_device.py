"""Device-path rules (ISSUE 20): GL14 jit-cache-key-leak, GL15
unpadded-device-launch, GL16 loop-touch-from-stage-thread.

All three encode the shape-stability and threading discipline
DEVICE_PATH.md documents as prose, scoped to the device path itself
(`block/`, `ops/`, `parallel/`) where the invariants are load-bearing:

  * GL14 is the PR 11 leak class: a jit/compile cache keyed on
    data-dependent runtime values — an erasure pattern, a present/
    missing set — compiles one program PER PATTERN (C(n,k) executables
    for RS(n,k) instead of one). The fix is always the same: key on
    shapes/counts (pad-bucket-derived values are exempt by
    construction) and ship the pattern as a tensor operand
    ("pattern-as-data", ops/rs.py's `gf_apply_batched`).
  * GL15 is the variable-shape trap: a `device_put` / batched-kernel
    launch whose operand was sized from raw lengths (`len(...)`,
    `max(...)`) instead of routing through the `pad_buckets` ladder
    (`bucket_items` / `bucket_len`) — every distinct size is a fresh
    XLA compile and a cache entry that never repeats.
  * GL16 is the stage-thread/loop boundary: functions the
    StageExecutor runs on its worker threads must never touch asyncio
    primitives directly (`call_soon`, `create_task`, `set_result`,
    ...) — the loop is not thread-safe; the ONLY sanctioned crossings
    are `call_soon_threadsafe` / `run_coroutine_threadsafe`
    (device_backend.py's delivery seam).
"""

from __future__ import annotations

import ast
import re

from .core import FileContext, ProjectState, Rule, Violation, \
    chain_segments
from .rules_dataflow import _dataflow, _is_checked_file

# the device path: where shape stability and stage-thread discipline
# are load-bearing (matches the ISSUE 20 scope — api/model/qos code
# has no jit caches or stage threads to misuse)
_DEVICE_PREFIXES = ("garage_tpu/block/", "garage_tpu/ops/",
                    "garage_tpu/parallel/")

# data-dependent erasure-pattern identifiers: the values PR 11's leak
# keyed a jit cache on (a tuple of shard indices changes per request;
# a shape or count does not)
_PATTERN_RE = re.compile(r"(^|_)(present|missing|pattern|patterns)($|_)")

# identifiers that mark a value as routed through the pad ladder
_PAD_SOURCES = {"bucket_items", "bucket_len"}
_PAD_NAME_RE = re.compile(r"(^|_)pad")

# array allocators whose shape arguments decide the compiled program
_ALLOC_METHODS = {"zeros", "empty", "ones", "full", "frombuffer",
                  "zeros_like", "empty_like"}

# raw-size evidence inside an allocation's shape arguments
_SIZE_CALLS = {"len", "max"}

# loop-affine asyncio primitives a stage thread must not touch; the
# *_threadsafe crossings are sanctioned by name
_UNSAFE_LOOP_CALLS = {"call_soon", "call_at", "call_later",
                      "create_task", "ensure_future", "create_future",
                      "set_result", "set_exception", "put_nowait"}

# methods a *Backend class runs on the stage executor's worker threads
_STAGE_METHODS = {"stage", "compute", "readback"}


def _device_scoped(rel_path: str) -> bool:
    # segment-anchored rather than startswith so files scanned from
    # outside the repo root (rel_path led by ../) still scope
    p = "/" + rel_path.replace("\\", "/")
    return any(f"/{pfx}" in p for pfx in _DEVICE_PREFIXES)


def _own_scopes(root: ast.AST):
    """Yield (scope_node, [statements]) for the module/function and
    every function under it — each function's body is ONE scope; its
    nested defs are their own."""
    yield root, _scope_stmts(root)
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n, _scope_stmts(n)
        if not isinstance(n, ast.Lambda):
            stack.extend(ast.iter_child_nodes(n))


def _scope_stmts(scope: ast.AST) -> list:
    """Statements of a scope in source order, flattened through
    compound statements but NOT into nested defs/lambdas."""
    out = []
    stack = list(getattr(scope, "body", []))[::-1]
    while stack:
        st = stack.pop()
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda, ast.ClassDef)):
            continue
        out.append(st)
        for field in ("body", "orelse", "finalbody"):
            stack.extend(list(getattr(st, field, []))[::-1])
        for h in getattr(st, "handlers", []):
            stack.extend(list(h.body)[::-1])
    return out


def _walk_scope_exprs(node: ast.AST, skip_len: bool = False):
    """Walk an expression/statement without descending into nested
    defs/lambdas; optionally skip len()/max() call arguments (a
    len(pattern) key is a COUNT, not the pattern)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)) and n is not node:
            continue
        if skip_len and isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Name) \
                and n.func.id in _SIZE_CALLS:
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _pattern_names(expr: ast.AST) -> list[str]:
    """Pattern-named identifiers in an expression, excluding those
    consumed by len()/max() (counts are shape-stable)."""
    names = {n.id for n in _walk_scope_exprs(expr, skip_len=True)
             if isinstance(n, ast.Name) and _PATTERN_RE.search(n.id)}
    return sorted(names)


class JitCacheKeyLeak(Rule):
    id = "GL14"
    name = "jit-cache-key-leak"
    summary = ("a jit/compile cache keyed on data-dependent runtime "
               "values — an `@lru_cache` whose parameters carry an "
               "erasure pattern (present/missing sets) into a jitted "
               "body, or a cache subscript whose key f-string/tuple "
               "embeds one — compiles one program PER PATTERN (the "
               "PR 11 leak: C(n,k) executables for RS(n,k)); key on "
               "shapes/counts (pad-bucket values are exempt) and ship "
               "the pattern as a tensor operand")
    rationale = (
        "PR 11 hand-fixed exactly this: the decode path cached jitted "
        "programs under `f\"dec{k},{m},{present}\"`, so RS(10,4) "
        "could compile and retain 1001 distinct executables — the "
        "compile cache became an unbounded leak keyed on request "
        "data. The discipline that replaced it (ops/rs.py "
        "`gf_apply_batched`) keys compiles on SHAPES only and passes "
        "the pattern as a device tensor, so the 1001 patterns share "
        "one program. This rule pins that discipline: an lru_cache/"
        "cache-decorated function whose parameters match present/"
        "missing/pattern AND whose body builds a jit program fires, "
        "as does a subscript store/load on a jit/compile-cache "
        "container whose key expression embeds a pattern-named "
        "value. `len(present)` keys are counts (shape-stable) and "
        "stay quiet, as do pad-bucket-derived shape keys.")
    example_fire = ("@functools.lru_cache(maxsize=None)\n"
                    "def make_step(mesh, k, m, present, missing):\n"
                    "    return jax.jit(step)   # one program/pattern")
    example_ok = ("@functools.lru_cache(maxsize=None)\n"
                  "def make_step(mesh, k, m, shard_len):\n"
                  "    return jax.jit(step)  # shape-keyed\n"
                  "# pattern ships as data: step(bitmats_t, shards)")

    def applies_to(self, ctx: FileContext) -> bool:
        return not ctx.is_test and _device_scoped(ctx.rel_path)

    def finish_file(self, ctx: FileContext) -> None:
        for scope, stmts in _own_scopes(ctx.tree):
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_cached_def(ctx, scope)
            self._check_key_subscripts(ctx, scope, stmts)

    # -- part A: @lru_cache def with pattern params + jitted body --------

    def _check_cached_def(self, ctx: FileContext,
                          node: ast.AST) -> None:
        cached = any(
            segs and segs[-1] in ("lru_cache", "cache")
            for d in node.decorator_list
            for segs in [chain_segments(d)])
        if not cached:
            return
        a = node.args
        pat = sorted(arg.arg for arg in
                     (a.posonlyargs + a.args + a.kwonlyargs)
                     if _PATTERN_RE.search(arg.arg))
        if not pat:
            return
        jitted = any(
            isinstance(n, (ast.Name, ast.Attribute))
            and "jit" in (n.id if isinstance(n, ast.Name)
                          else n.attr).lower()
            for st in node.body for n in _walk_scope_exprs(st))
        if not jitted:
            return
        ctx.report(self.id, node, (
            f"`@lru_cache` on `{node.name}` is keyed on data-dependent "
            f"pattern parameter(s) {', '.join(pat)} while the body "
            "builds a jit program — one compiled executable per "
            "pattern (the PR 11 leak class, C(n,k) programs for "
            "RS(n,k)); key the cache on shapes/counts and ship the "
            "pattern as a tensor operand"))

    # -- part B: cache[key] with a pattern baked into the key ------------

    def _check_key_subscripts(self, ctx: FileContext, scope: ast.AST,
                              stmts: list) -> None:
        key_vars: dict[str, ast.AST] = {}
        for st in stmts:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name) \
                    and isinstance(st.value, (ast.JoinedStr, ast.Tuple)):
                key_vars[st.targets[0].id] = st.value
        for st in stmts:
            for n in _walk_scope_exprs(st):
                if not isinstance(n, ast.Subscript):
                    continue
                segs = chain_segments(n.value)
                if not any("cache" in s.lower() or "jit" in s.lower()
                           for s in segs):
                    continue
                key = n.slice
                if isinstance(key, ast.Name) and key.id in key_vars:
                    key = key_vars[key.id]
                names = _pattern_names(key)
                if names:
                    ctx.report(self.id, n, (
                        f"compile-cache key on `{'.'.join(segs)}` "
                        f"embeds data-dependent value(s) "
                        f"{', '.join(names)} — one cached program per "
                        "pattern (the PR 11 leak); use shape/count "
                        "keys and pass the pattern as data"))


class UnpaddedDeviceLaunch(Rule):
    id = "GL15"
    name = "unpadded-device-launch"
    summary = ("a device_put / batched-kernel launch whose operand was "
               "sized from raw lengths (len()/max()) instead of the "
               "pad_buckets ladder (bucket_items/bucket_len) — every "
               "distinct size is a fresh XLA compile that never "
               "repeats; round sizes through the bucket helpers so the "
               "shape set stays closed")
    rationale = (
        "XLA compiles per SHAPE: feeding a device a (n_blobs, "
        "max_len) array sized straight from the request recompiles "
        "on nearly every batch and fills the compile cache with "
        "programs that never repeat (DEVICE_PATH.md's variable-shape "
        "trap — the reason the pad-bucket ladder exists). The feeder "
        "discipline routes every staged shape through bucket_items/"
        "bucket_len so the reachable shape set is small and closed, "
        "and zero new compiles happen after warmup. This rule flags "
        "an operand allocated with raw len()/max() sizes reaching "
        "device_put or the batched GF kernel without touching the "
        "ladder.")
    example_fire = ("buf = np.zeros((len(blobs), max_len))\n"
                    "dev = jax.device_put(buf)  # shape per request")
    example_ok = ("b, padded = bucket_items(len(blobs), buckets)\n"
                  "buf = np.zeros((b, padded))\n"
                  "dev = jax.device_put(buf)  # bucketed shape")

    def applies_to(self, ctx: FileContext) -> bool:
        return not ctx.is_test and _device_scoped(ctx.rel_path)

    def finish_file(self, ctx: FileContext) -> None:
        for scope, stmts in _own_scopes(ctx.tree):
            self._check_scope(ctx, stmts)

    def _assigned_names(self, target: ast.AST) -> list[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            return [e.id for e in target.elts if isinstance(e, ast.Name)]
        return []

    def _check_scope(self, ctx: FileContext, stmts: list) -> None:
        padded: set[str] = set()
        raw: set[str] = set()
        # two monotone passes so `smax` defined after first use of the
        # helper chain still classifies (mirrors pass 1's taint walk)
        for _ in range(2):
            for st in stmts:
                if not isinstance(st, ast.Assign):
                    continue
                names = [n for t in st.targets
                         for n in self._assigned_names(t)]
                if not names:
                    continue
                v = st.value
                mentions = {n.id for n in _walk_scope_exprs(v)
                            if isinstance(n, ast.Name)}
                pad_call = any(
                    isinstance(n, ast.Call) and (
                        (cs := chain_segments(n.func))
                        and (cs[-1] in _PAD_SOURCES
                             or _PAD_NAME_RE.search(cs[-1])))
                    for n in _walk_scope_exprs(v))
                if pad_call or mentions & padded:
                    padded.update(names)
                    raw.difference_update(names)
                    continue
                alloc = isinstance(v, ast.Call) and (
                    (cs := chain_segments(v.func))
                    and cs[-1] in _ALLOC_METHODS)
                size_call = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id in _SIZE_CALLS
                    for n in _walk_scope_exprs(v))
                if (alloc and size_call) or mentions & raw:
                    raw.update(names)
        for st in stmts:
            for n in _walk_scope_exprs(st):
                if not isinstance(n, ast.Call):
                    continue
                segs = chain_segments(n.func)
                cname = segs[-1] if segs else ""
                if cname not in ("device_put", "gf_apply_batched"):
                    continue
                for a in n.args:
                    if isinstance(a, ast.Name) and a.id in raw \
                            and a.id not in padded:
                        ctx.report(self.id, n, (
                            f"`{cname}({a.id})` launches an operand "
                            f"sized from raw len()/max() — `{a.id}` "
                            "never routed through the pad_buckets "
                            "ladder (bucket_items/bucket_len), so "
                            "every distinct size compiles a fresh "
                            "program; round the shape through the "
                            "bucket helpers"))
                        break


class LoopTouchFromStageThread(Rule):
    id = "GL16"
    name = "loop-touch-from-stage-thread"
    needs_dataflow = True
    summary = ("code reachable on a device stage thread (a *Backend "
               "stage/compute/readback method, or a function submitted "
               "to the stage executor) calls a loop-affine asyncio "
               "primitive (call_soon, create_task, set_result, ...) — "
               "the event loop is not thread-safe off-loop; the only "
               "sanctioned crossings are call_soon_threadsafe / "
               "run_coroutine_threadsafe")
    rationale = (
        "DevicePipeline runs each stage on a dedicated worker thread "
        "(StageExecutor); the asyncio loop those stages report back "
        "to lives on the main thread. Every asyncio primitive except "
        "the *_threadsafe pair assumes it is called ON the loop "
        "thread — a stage function calling loop.call_soon or "
        "fut.set_result directly corrupts the loop's internal state "
        "or races its wakeup pipe, and the failure is a heisenbug "
        "(device_backend.py's delivery seam exists precisely to "
        "funnel results through loop.call_soon_threadsafe). The rule "
        "walks sync call-graph edges from every stage-executed root "
        "and flags loop-affine calls it can reach.")
    example_fire = ("class JaxDeviceBackend:\n"
                    "    def readback(self, op, handle):\n"
                    "        self.loop.call_soon(self._deliver, out)")
    example_ok = ("class JaxDeviceBackend:\n"
                  "    def readback(self, op, handle):\n"
                  "        self.loop.call_soon_threadsafe(\n"
                  "            self._deliver, out)")

    def finish_project(self, project: ProjectState) -> list[Violation]:
        df = _dataflow(project)
        if df is None:
            return []
        g = df.graph
        file_ok: dict[str, bool] = {}

        def checked(path: str) -> bool:
            if path not in file_ok:
                file_ok[path] = _is_checked_file(project, path)
            return file_ok[path]

        roots: dict[str, str] = {}
        for fid in sorted(g.functions):
            fn = g.functions[fid]
            if not _device_scoped(fn["path"]) or not checked(fn["path"]):
                continue
            cls = fn.get("class") or ""
            if cls.endswith("Backend") and fn["name"] in _STAGE_METHODS:
                roots.setdefault(
                    fid, f"`{cls}.{fn['name']}` runs on a stage "
                         "executor worker thread")
            for rec in fn["calls"]:
                if rec["name"] != "submit":
                    continue
                for ad in rec["args"]:
                    if not ad or "n" not in ad:
                        continue
                    cal = g.resolve_ref(fid, ["name", ad["n"]])
                    if cal is not None:
                        roots.setdefault(
                            cal, f"submitted to the stage executor in "
                                 f"`{fn['qualname']}`")

        out: list[Violation] = []
        fired: set[tuple] = set()
        for root in sorted(roots):
            why = roots[root]
            seen: set[str] = set()
            stack = [root]
            while stack:
                cur = stack.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                fn = g.functions[cur]
                if checked(fn["path"]):
                    for rec in fn["calls"]:
                        if rec["name"] in _UNSAFE_LOOP_CALLS \
                                and rec["recv"]:
                            key = (fn["path"], rec["line"], rec["name"])
                            if key in fired:
                                continue
                            fired.add(key)
                            out.append(Violation(
                                rule=self.id, path=fn["path"],
                                line=rec["line"], col=0,
                                message=(
                                    f"asyncio `{rec['name']}` called "
                                    "from code reachable on a device "
                                    f"stage thread ({why}) — loop-"
                                    "affine primitives are not thread-"
                                    "safe off-loop; cross via loop."
                                    "call_soon_threadsafe(...) or "
                                    "asyncio.run_coroutine_threadsafe"
                                    "(...)"),
                                context=fn["qualname"]))
                for nxt, rec in g.edges_from(cur):
                    if rec["via_thread"] or rec["awaited"]:
                        continue
                    if g.functions[nxt]["is_async"]:
                        continue
                    stack.append(nxt)
        return out
