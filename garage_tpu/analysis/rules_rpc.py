"""Hedge-safety and SSE-C cache rules: GL02 hedge-on-mutation, GL03
ssec-cache-leak. Both are dataflow-backed since ISSUE 9.

GL02 generalizes PR 4's hand-pinned k2v `hedge=False`: a hedged RPC
races a second copy of the request, so a non-idempotent (write/insert/
delete) endpoint must never be called with hedging possible — a
slow-but-alive node would apply the mutation twice (duplicate DVVS
siblings was the concrete k2v failure). Three triggers:

  (a) `RequestStrategy(..., hedge=True)` anywhere — explicitly forcing
      hedges is only ever safe on idempotent reads and needs a waiver
      saying so;
  (b) a hedge-DEFAULTING `try_call_many` (no `hedge=` in its strategy)
      in a mutation context: the enclosing function, or an `op` string
      in the payload, matches write/insert/delete patterns;
  (c) interprocedural (the ROADMAP upgrade): a helper whose `strategy`
      PARAMETER feeds a mutating `try_call_many` makes that parameter
      hedge-sensitive — every caller that passes an unpinned
      `RequestStrategy(...)` into it is flagged AT THE CALLER, where
      the missing `hedge=False` belongs. Sensitivity propagates up
      through param-to-param forwarding (fixpoint over the call graph).

GL03 is true SSE-C taint tracking since ISSUE 9 (the PR 5 cut keyed on
an `sse`-*named* binding in scope). Sources: sse-named params/locals
and decrypt results. The taint crosses helper boundaries: an argument
built from SSE-C state taints the callee's parameter (whatever it is
named), to a fixpoint. Sinks, in api/s3/ + block/ + gateway/: any call
through the block-manager cache seam (`rpc_get_block`/`rpc_put_block`)
from a tainted scope without an explicit `cacheable=`, and any tainted
payload handed to a cache `insert`. The PR 3 invariant stands: SSE-C
plaintext never outlives the request in the node-local read cache, and
the explicit kwarg is the audit point."""

from __future__ import annotations

import ast
import re

from .core import (MUTATION_NAME_RE, MUTATION_OP_RE, FileContext,
                   ProjectState, Rule, Violation, call_name, is_const,
                   kwarg, payload_ops)


def _strategy_of(node: ast.Call, ctx: FileContext) -> "ast.Call | str | None":
    """Resolve the RequestStrategy expression of a try_call_many call:
    inline constructor (positional arg 3 / kw `strategy`), a local
    `name = RequestStrategy(...)` binding recorded by the walker, or
    the sentinel "param" when the strategy arrives as a function
    parameter (resolved interprocedurally in finish_project)."""
    expr = kwarg(node, "strategy")
    if expr is None and len(node.args) >= 4:
        expr = node.args[3]
    if isinstance(expr, ast.Call) and call_name(expr) == "RequestStrategy":
        return expr
    if isinstance(expr, ast.Name):
        local = ctx.func_meta.get("strategies", {}).get(expr.id)
        if local is not None:
            return local
        if expr.id in ctx.func_meta.get("args", set()):
            return "param"
    return None


def _mutating_ops(ops: list[str]) -> bool:
    return any(MUTATION_OP_RE.match(o) for o in ops)


class HedgeOnMutation(Rule):
    id = "GL02"
    name = "hedge-on-mutation"
    needs_dataflow = True
    summary = ("hedge=True, a hedge-defaulting try_call_many on a "
               "write/insert/delete endpoint, or an unpinned strategy "
               "passed into a mutating helper — a hedged mutation can "
               "apply twice (the PR 4 k2v duplicate-siblings bug); "
               "pin hedge=False on non-idempotent RPCs")
    rationale = (
        "A hedged RPC races a second copy of the request against a "
        "slow-but-alive node — harmless on idempotent reads, double-"
        "apply on mutations (the concrete PR 4 failure: duplicate "
        "DVVS siblings in k2v). Since ISSUE 9 the rule resolves "
        "strategies across function boundaries: a helper whose "
        "strategy parameter feeds a mutating try_call_many makes "
        "every unpinned caller a finding AT THE CALLER.")
    example_fire = ("async def insert(self, who, payload):\n"
                    "    await self._call_any(who, payload,\n"
                    "                         RequestStrategy(quorum=1))")
    example_ok = ("async def insert(self, who, payload):\n"
                  "    await self._call_any(who, payload,\n"
                  "        RequestStrategy(quorum=1, hedge=False))")

    def on_call(self, node: ast.Call, ctx: FileContext) -> None:
        name = call_name(node)
        if name == "RequestStrategy":
            if is_const(kwarg(node, "hedge"), True):
                ctx.report(self.id, node,
                           "RequestStrategy(hedge=True): forcing "
                           "hedges is only safe on idempotent reads; "
                           "waive with that justification or drop it")
            return
        if name != "try_call_many":
            return
        strategy = _strategy_of(node, ctx)
        if strategy == "param":
            return  # resolved interprocedurally at the caller
        if isinstance(strategy, ast.Call) \
                and kwarg(strategy, "hedge") is not None:
            return  # explicit pin (True already flagged above)
        func_name = ctx.func_stack[-1][1] if ctx.func_stack else ""
        mutating = bool(MUTATION_NAME_RE.search(func_name))
        ops = payload_ops(node)
        mutating = mutating or _mutating_ops(ops)
        if mutating:
            why = (f"op {ops!r}" if ops and _mutating_ops(ops)
                   else f"enclosing `{func_name}`")
            ctx.report(self.id, node,
                       "hedge-defaulting try_call_many in mutation "
                       f"context ({why}); pass RequestStrategy("
                       "hedge=False) — a hedged write can apply twice")

    # ---- interprocedural strategy resolution (trigger c) ---------------

    def finish_project(self, project: ProjectState) -> list[Violation]:
        df = project.data.get("_dataflow")
        if df is None:
            return []
        graph = df.graph
        # seed: (function id, param name) pairs whose param feeds a
        # hedge-defaulting try_call_many. Two tiers: "mut" when the
        # CALLEE's own context (enclosing name / payload op) is already
        # mutating — every unpinned caller fires; "any" when the callee
        # is context-neutral plumbing — the CALLER fires only if its
        # own context is mutating.
        sensitive: dict[tuple, tuple] = {}   # (fid, param) -> (tier, why)
        for fid in sorted(graph.functions):
            fn = graph.functions[fid]
            for rec in fn["calls"]:
                if rec["name"] != "try_call_many":
                    continue
                desc = rec["kw"].get("strategy")
                if desc is None and len(rec["args"]) >= 4 \
                        and rec["args"][3] is not None:
                    desc = rec["args"][3]
                s = (desc or {}).get("s")
                if not s or s.get("k") != "param":
                    continue
                tier = ("mut" if fn["mutation_name"]
                        or _mutating_ops(rec["ops"]) else "any")
                why = (f"{fn['qualname']} (try_call_many at "
                       f"{fn['path']}:{rec['line']})")
                cur = sensitive.get((fid, s["name"]))
                if cur is None or (cur[0] == "any" and tier == "mut"):
                    sensitive[(fid, s["name"])] = (tier, why)
        # propagate param-to-param forwarding up the call graph
        changed = True
        while changed:
            changed = False
            for fid in graph.functions:
                fn = graph.functions[fid]
                for callee, rec in graph.edges_from(fid):
                    shift = graph.bound_call(fid, rec)
                    for pos, desc in enumerate(rec["args"]):
                        s = (desc or {}).get("s")
                        if not s or s.get("k") != "param":
                            continue
                        pname = graph.param_index(callee, pos, shift)
                        hit = sensitive.get((callee, pname)) \
                            if pname else None
                        if hit is None:
                            continue
                        cur = sensitive.get((fid, s["name"]))
                        if cur is None or (cur[0] == "any"
                                           and hit[0] == "mut"):
                            sensitive[(fid, s["name"])] = hit
                            changed = True
        if not sensitive:
            return []
        # fire: unpinned strategies constructed at a call into a
        # sensitive parameter
        out: list[Violation] = []
        test_paths = {c.rel_path for c in project.files
                      if c.is_test or c.is_harness}
        for fid in sorted(graph.functions):
            fn = graph.functions[fid]
            if fn["path"] in test_paths:
                continue
            for callee, rec in graph.edges_from(fid):
                shift = graph.bound_call(fid, rec)
                args = list(enumerate(rec["args"])) + [
                    (k, d) for k, d in sorted(rec["kw"].items())]
                for pos, desc in args:
                    s = (desc or {}).get("s")
                    if not s or s.get("k") not in ("inline", "local"):
                        continue
                    if s.get("hedge") is not None:
                        continue  # pinned (True fired in on_call)
                    pname = (graph.param_index(callee, pos, shift)
                             if isinstance(pos, int) else pos)
                    hit = sensitive.get((callee, pname)) if pname else None
                    if hit is None:
                        continue
                    tier, why = hit
                    if tier == "any" and not (
                            fn["mutation_name"]
                            or _mutating_ops(rec["ops"])):
                        continue
                    v = Violation(
                        rule=self.id, path=fn["path"],
                        line=rec["line"], col=0,
                        message=(
                            "unpinned RequestStrategy passed into "
                            f"hedge-sensitive `{pname}` of {why}; pass "
                            "hedge=False — a hedged write can apply "
                            "twice"),
                        context=fn["qualname"])
                    v._end_line = rec.get("end_line")  # type: ignore
                    out.append(v)
        return out


GL03_DIRS = re.compile(r"(^|/)(api/s3|block|gateway)/")
SSE_NAME_RE = re.compile(r"(^|_)sse", re.IGNORECASE)
CACHE_SEAM = {"rpc_get_block", "rpc_put_block"}
# the CLUSTER cache tier's cross-node seam (ISSUE 15,
# block/cache_tier.py): `probe` on a tier/cache receiver must carry the
# same explicit cacheable= audit flag as the rpc_get/put_block seam —
# an SSE-C hash must never even be ASKED about across nodes — and
# `insert_at` is a cache-insert sink like `.insert`. ISSUE 18 widened
# the seam: `probe_full` is the lease-carrying GET form and
# `probe_packed` hits the packed-bytes segment — same audit flag, same
# rule (an SSE-C hash must not mint a lease or pull packed bytes
# either).
TIER_PROBE_NAMES = {"probe", "probe_full", "probe_packed",
                    "cache_tier_probe"}
CACHE_INSERT_NAMES = {"insert", "insert_at", "cache_tier_insert"}
_SSEISH = ("<sse>", "<decrypt>")


def _cacheish_recv(recv) -> bool:
    return any("cache" in s.lower() or "tier" in s.lower()
               for s in recv)


class SsecCacheLeak(Rule):
    id = "GL03"
    name = "ssec-cache-leak"
    needs_dataflow = True
    summary = ("SSE-C taint reaching the block cache seam without an "
               "explicit cacheable=, or a tainted payload inserted "
               "into a cache — PR 3's invariant is that SSE-C "
               "plaintext never enters the node-local read cache; the "
               "taint follows the value across helper boundaries")
    rationale = (
        "SSE-C plaintext must never outlive its request in a shared "
        "cache (PR 3's invariant; the explicit cacheable= kwarg is "
        "the audit point). PR 5's cut keyed on an sse-NAMED binding "
        "in scope; since ISSUE 9 this is real taint tracking — "
        "sse-named params/locals and decrypt results taint every "
        "argument built from them, the taint crosses helper "
        "boundaries to a fixpoint, and a helper that receives SSE-C "
        "state under ANY parameter name must pass cacheable= at the "
        "seam.")
    example_fire = ("async def helper(mgr, h, key):      # key <- sse_key\n"
                    "    return await mgr.rpc_get_block(h)\n"
                    "async def stream(mgr, h, sse_key):\n"
                    "    return await helper(mgr, h, sse_key)")
    example_ok = ("async def helper(mgr, h, key):\n"
                  "    return await mgr.rpc_get_block(\n"
                  "        h, cacheable=key is None)")

    def applies_to(self, ctx: FileContext) -> bool:
        # the rule itself runs in finish_project; applies_to only
        # gates the (unused) per-file hooks
        return not ctx.is_test

    def finish_project(self, project: ProjectState) -> list[Violation]:
        df = project.data.get("_dataflow")
        if df is None:
            return []
        graph = df.graph
        # fixpoint: parameters that receive SSE-C state from any caller
        tainted: dict[tuple, str] = {}   # (fid, param) -> provenance

        def fn_sse_labels(fid: str, fn: dict) -> set:
            labels = set(fn["sse_sources"])
            labels |= {p for p in fn["params"] if (fid, p) in tainted}
            return labels

        changed = True
        while changed:
            changed = False
            for fid in graph.functions:
                fn = graph.functions[fid]
                live = fn_sse_labels(fid, fn)
                for callee, rec in graph.edges_from(fid):
                    shift = graph.bound_call(fid, rec)
                    args = list(enumerate(rec["args"])) + [
                        (k, d) for k, d in sorted(rec["kw"].items())]
                    for pos, desc in args:
                        t = (desc or {}).get("t")
                        if not t:
                            continue
                        if not (set(t) & (live | set(_SSEISH))):
                            continue
                        pname = (graph.param_index(callee, pos, shift)
                                 if isinstance(pos, int) else pos)
                        if pname and (callee, pname) not in tainted:
                            tainted[(callee, pname)] = (
                                f"tainted via {fn['qualname']} at "
                                f"{fn['path']}:{rec['line']}")
                            changed = True
        # sinks
        out: list[Violation] = []
        test_paths = {c.rel_path for c in project.files
                      if c.is_test or c.is_harness}
        for fid in sorted(graph.functions):
            fn = graph.functions[fid]
            if fn["path"] in test_paths \
                    or not GL03_DIRS.search(fn["path"]):
                continue
            live = fn_sse_labels(fid, fn)
            if not live:
                continue
            origin = ""
            for p in fn["params"]:
                if (fid, p) in tainted:
                    origin = f" ({tainted[(fid, p)]})"
                    break
            for rec in fn["calls"]:
                if (rec["name"] in CACHE_SEAM
                        or (rec["name"] in TIER_PROBE_NAMES
                            and _cacheish_recv(rec["recv"]))) \
                        and "cacheable" not in rec["kwargs"]:
                    v = Violation(
                        rule=self.id, path=fn["path"], line=rec["line"],
                        col=0,
                        message=(
                            f"`{rec['name']}` in an SSE-C scope without "
                            "explicit cacheable=; pass cacheable="
                            "(sse_key is None) so encrypted payloads "
                            "never enter the read cache (or cross a "
                            f"node on the tier probe){origin}"),
                        context=fn["qualname"])
                    v._end_line = rec.get("end_line")  # type: ignore
                    out.append(v)
                    continue
                if rec["name"] in CACHE_INSERT_NAMES \
                        and _cacheish_recv(rec["recv"]):
                    hot = set()
                    for desc in list(rec["args"]) + \
                            list(rec["kw"].values()):
                        t = (desc or {}).get("t") or []
                        hot |= set(t) & (live | set(_SSEISH))
                    if hot:
                        v = Violation(
                            rule=self.id, path=fn["path"],
                            line=rec["line"], col=0,
                            message=(
                                "SSE-C-tainted payload inserted into a "
                                f"cache (labels {sorted(hot)}); SSE-C "
                                "plaintext must never enter a shared "
                                f"cache{origin}"),
                            context=fn["qualname"])
                        v._end_line = rec.get("end_line")  # type: ignore
                        out.append(v)
        return out
