"""Hedge-safety and SSE-C cache rules: GL02 hedge-on-mutation, GL03
ssec-cache-leak.

GL02 generalizes PR 4's hand-pinned k2v `hedge=False`: a hedged RPC
races a second copy of the request, so a non-idempotent (write/insert/
delete) endpoint must never be called with hedging possible — a
slow-but-alive node would apply the mutation twice (duplicate DVVS
siblings was the concrete k2v failure). Two triggers:

  (a) `RequestStrategy(..., hedge=True)` anywhere — explicitly forcing
      hedges is only ever safe on idempotent reads and needs a waiver
      saying so;
  (b) a hedge-DEFAULTING `try_call_many` (no `hedge=` in its strategy)
      in a mutation context: the enclosing function, or an `op` string
      in the payload, matches write/insert/delete patterns.

GL03 is syntactic-first (ROADMAP notes the dataflow upgrade): in
api/s3/ and block/, any call through the block-manager cache seam
(`rpc_get_block` / `rpc_put_block`) from a scope that has SSE-C state
in hand (a name matching `sse`) must pass `cacheable=` explicitly —
the PR 3 invariant is that SSE-C plaintext never outlives the request
in the node-local read cache, and the explicit kwarg is the audit
point.
"""

from __future__ import annotations

import ast
import re

from .core import (FileContext, Rule, call_name, is_const, kwarg)

MUTATION_NAME_RE = re.compile(
    r"(^|_)(insert|write|put|delete|update|remove|push|apply|store|"
    r"flush|merge)($|_)")
MUTATION_OP_RE = re.compile(
    r"^(insert|write|put|delete|update|remove|push|apply|store|flush)")


def _strategy_of(node: ast.Call, ctx: FileContext) -> ast.Call | None:
    """Resolve the RequestStrategy expression of a try_call_many call:
    inline constructor (positional arg 3 / kw `strategy`) or a local
    `name = RequestStrategy(...)` binding recorded by the walker."""
    expr = kwarg(node, "strategy")
    if expr is None and len(node.args) >= 4:
        expr = node.args[3]
    if isinstance(expr, ast.Call) and call_name(expr) == "RequestStrategy":
        return expr
    if isinstance(expr, ast.Name):
        return ctx.func_meta.get("strategies", {}).get(expr.id)
    return None


def _payload_ops(node: ast.Call) -> list[str]:
    """Constant `op` strings found anywhere in the call's payload
    arguments (table RPCs ship {'op': 'insert_many', ...} dicts)."""
    ops = []
    for arg in list(node.args) + [k.value for k in node.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Dict):
                for k, v in zip(sub.keys, sub.values):
                    if is_const(k) and k.value == "op" \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        ops.append(v.value)
    return ops


class HedgeOnMutation(Rule):
    id = "GL02"
    name = "hedge-on-mutation"
    summary = ("hedge=True, or a hedge-defaulting try_call_many on a "
               "write/insert/delete endpoint — a hedged mutation can "
               "apply twice (the PR 4 k2v duplicate-siblings bug); "
               "pin hedge=False on non-idempotent RPCs")

    def on_call(self, node: ast.Call, ctx: FileContext) -> None:
        name = call_name(node)
        if name == "RequestStrategy":
            if is_const(kwarg(node, "hedge"), True):
                ctx.report(self.id, node,
                           "RequestStrategy(hedge=True): forcing "
                           "hedges is only safe on idempotent reads; "
                           "waive with that justification or drop it")
            return
        if name != "try_call_many":
            return
        strategy = _strategy_of(node, ctx)
        if strategy is not None and kwarg(strategy, "hedge") is not None:
            return  # explicit pin (True already flagged above)
        func_name = ctx.func_stack[-1][1] if ctx.func_stack else ""
        mutating = bool(MUTATION_NAME_RE.search(func_name))
        ops = _payload_ops(node)
        mutating = mutating or any(MUTATION_OP_RE.match(o) for o in ops)
        if mutating:
            why = (f"op {ops!r}" if ops and any(
                MUTATION_OP_RE.match(o) for o in ops)
                else f"enclosing `{func_name}`")
            ctx.report(self.id, node,
                       "hedge-defaulting try_call_many in mutation "
                       f"context ({why}); pass RequestStrategy("
                       "hedge=False) — a hedged write can apply twice")


GL03_DIRS = re.compile(r"(^|/)(api/s3|block)/")
SSE_NAME_RE = re.compile(r"(^|_)sse", re.IGNORECASE)
CACHE_SEAM = {"rpc_get_block", "rpc_put_block"}


class SsecCacheLeak(Rule):
    id = "GL03"
    name = "ssec-cache-leak"
    summary = ("block read/write through the cache seam from an SSE-C "
               "scope without an explicit cacheable= — PR 3's "
               "invariant is that SSE-C payloads never enter the "
               "node-local read cache")

    def applies_to(self, ctx: FileContext) -> bool:
        return (not ctx.is_test) and bool(GL03_DIRS.search(ctx.rel_path))

    def on_call(self, node: ast.Call, ctx: FileContext) -> None:
        if call_name(node) not in CACHE_SEAM:
            return
        meta = ctx.func_meta
        names = meta.get("args", set()) | meta.get("assigned", set())
        if not any(SSE_NAME_RE.search(n) for n in names):
            return
        if kwarg(node, "cacheable") is None:
            ctx.report(self.id, node,
                       f"`{call_name(node)}` in an SSE-C scope without "
                       "explicit cacheable=; pass cacheable=(sse_key "
                       "is None) so encrypted payloads never enter "
                       "the read cache")
