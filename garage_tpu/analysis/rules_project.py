"""Project-contract rules: GL07 unregistered-metric, GL08
config-knob-drift.

GL07 keeps the metric namespace auditable: every name handed to the
metrics registry must be a string LITERAL matching the
`<subsystem>_<snake_case>` scheme (regex shared with the runtime check
in utils/metrics.py, which rejects the same violations at registration
time under GARAGE_METRICS_STRICT=1 — the static rule and the runtime
agree by construction). A dynamically built name is flagged outright:
unbounded name cardinality is a slow memory leak and makes dashboards
unwriteable.

GL08 is the only genuinely cross-file rule: it parses the config
dataclasses out of utils/config.py during the normal pass and, in
finish_project, reconciles them against every `cfg.X` / `config.X` /
`cfg.<section>.Y` read in the tree — a knob read in code but absent
from the defaults is a typo that silently yields AttributeError at
runtime; a default that nothing reads and the README never mentions is
dead weight (or a feature that quietly lost its wiring).
"""

from __future__ import annotations

import ast
import re

from ..utils.metrics import METRIC_NAME_RE
from .core import (FileContext, ProjectState, Rule, Violation, call_name,
                   chain_segments, is_const)

# ---- GL07 --------------------------------------------------------------

METRIC_METHODS = {"inc", "observe", "timer"}
METRIC_RECEIVERS = {"registry", "metrics_registry"}


class UnregisteredMetric(Rule):
    id = "GL07"
    name = "unregistered-metric"
    summary = ("metric name is dynamic or breaks the "
               "<subsystem>_<snake_case> scheme; the runtime strict "
               "check (utils/metrics.py) enforces the same regex")
    rationale = (
        "Dynamically built metric names are unbounded cardinality — a "
        "slow memory leak and an ungreppable dashboard. Names must be "
        "string literals matching the shared METRIC_NAME_RE; the "
        "runtime rejects the same names at registration under "
        "GARAGE_METRICS_STRICT=1, so the static and runtime checks "
        "agree by construction. Runs on harness files (bench emits "
        "metric names into reports).")
    example_fire = 'registry().inc(f"qos_{key}_total")   # per-key series'
    example_ok = 'registry().inc("qos_shed_requests", scope=key)'

    def applies_to(self, ctx: FileContext) -> bool:
        # the registry implementation itself passes names through
        return (not ctx.is_test
                and not ctx.rel_path.endswith("utils/metrics.py"))

    def on_call(self, node: ast.Call, ctx: FileContext) -> None:
        segs = chain_segments(node.func)
        if len(segs) < 2 or segs[-1] not in METRIC_METHODS:
            return
        if not any(s in METRIC_RECEIVERS for s in segs[:-1]):
            return
        if not node.args:
            return
        name = node.args[0]
        if not (is_const(name) and isinstance(name.value, str)):
            ctx.report(self.id, node,
                       f"dynamically constructed metric name passed to "
                       f"`{segs[-1]}`; metric names must be string "
                       "literals (bounded cardinality, greppable)")
            return
        if not METRIC_NAME_RE.match(name.value):
            ctx.report(self.id, node,
                       f"metric name {name.value!r} violates the "
                       f"naming scheme {METRIC_NAME_RE.pattern!r}")


# ---- GL08 --------------------------------------------------------------

CONFIG_RECEIVERS = {"cfg", "config"}
SECTION_ATTRS = {"tpu": "TpuConfig", "qos": "QosConfig",
                 "chaos": "ChaosConfig", "gateway": "GatewayConfig"}
CONFIG_CLASSES = ("Config", "TpuConfig", "QosConfig", "ChaosConfig",
                  "GatewayConfig", "DataDir")


def _config_receiver(node: ast.AST) -> bool:
    """`cfg` / `config` / `self.cfg` / `self.config` / `<x>.config`
    where the FINAL segment is the config name (never e.g.
    website_config)."""
    if isinstance(node, ast.Name):
        return node.id in CONFIG_RECEIVERS
    if isinstance(node, ast.Attribute):
        return node.attr in CONFIG_RECEIVERS
    return False


class ConfigKnobDrift(Rule):
    id = "GL08"
    name = "config-knob-drift"
    summary = ("config key read in code but absent from utils/config.py "
               "defaults, or a default that nothing reads and the "
               "README never documents")
    rationale = (
        "Both directions of knob drift are silent failures: a key "
        "read in code with no default is an AttributeError waiting "
        "for the one deployment that exercises it; a default nothing "
        "reads is a feature that quietly lost its wiring (PR 5 found "
        "two: metadata_fsync ignored, [tpu] batch_blocks dead). The "
        "rule reconciles every cfg.X / section alias / getattr read "
        "against the dataclass schema, cross-file.")
    example_fire = "return cfg.block_sizze    # typo: not a Config field"
    example_ok = "return cfg.block_size"

    def applies_to(self, ctx: FileContext) -> bool:
        return not ctx.is_test

    def __init__(self):
        # (attr, ctx_rel, lineno, col, qualname) for top-level reads;
        # section reads keyed by section name
        self.top_reads: list[tuple] = []
        self.section_reads: list[tuple] = []
        self.config_ctx: FileContext | None = None
        self.string_constants: set[str] = set()

    def finish_file(self, ctx: FileContext) -> None:
        """GL08 collects per-file in its own walk (it needs two
        ordered passes — alias discovery, then reads — which the
        shared single dispatch can't provide)."""
        if ctx.rel_path.endswith("utils/config.py"):
            self.config_ctx = ctx
            is_schema = True
        else:
            is_schema = False
        method_funcs: set[int] = set()
        # names locally bound to a config SECTION:  qc = cfg.qos
        aliases: dict[str, str] = {}
        for sub in ast.walk(ctx.tree):
            if isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                            str):
                self.string_constants.add(sub.value)
            elif isinstance(sub, ast.Call):
                # knobs are data, never called: `cfg.get(...)` is a
                # dict named cfg, not a knob read
                method_funcs.add(id(sub.func))
                if not is_schema and call_name(sub) == "getattr" \
                        and len(sub.args) >= 2 \
                        and isinstance(sub.args[0], ast.Name) \
                        and sub.args[0].id in CONFIG_RECEIVERS \
                        and is_const(sub.args[1]) \
                        and isinstance(sub.args[1].value, str):
                    self.top_reads.append(
                        (sub.args[1].value, ctx.rel_path, sub.lineno,
                         sub.col_offset, "<getattr>"))
            elif isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Attribute) \
                    and sub.value.attr in SECTION_ATTRS \
                    and _config_receiver(sub.value.value):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = sub.value.attr
                    elif isinstance(t, ast.Attribute):
                        # self.qos_cfg = cfg.qos — alias by attr name
                        aliases[t.attr] = sub.value.attr
        if is_schema:
            return  # the schema module reads itself freely
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute) \
                    or isinstance(node.ctx, (ast.Store, ast.Del)) \
                    or id(node) in method_funcs:
                continue
            at = (node.lineno, node.col_offset)
            v = node.value
            if _config_receiver(v):
                self.top_reads.append((node.attr, ctx.rel_path, *at,
                                       "<module>"))
            elif isinstance(v, ast.Attribute) and v.attr in SECTION_ATTRS \
                    and _config_receiver(v.value):
                self.section_reads.append((v.attr, node.attr,
                                           ctx.rel_path, *at, "<module>"))
            elif isinstance(v, ast.Name) and v.id in aliases:
                self.section_reads.append((aliases[v.id], node.attr,
                                           ctx.rel_path, *at, "<module>"))
            elif isinstance(v, ast.Attribute) and v.attr in aliases \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                self.section_reads.append((aliases[v.attr], node.attr,
                                           ctx.rel_path, *at, "<module>"))

    def finish_project(self, project: ProjectState) -> list[Violation]:
        if self.config_ctx is None:
            return []  # fixture runs without the schema in scope
        schema = _parse_config_schema(self.config_ctx.tree)
        readme = project.data.get("readme_text", "")
        out: list[Violation] = []
        top_fields, top_extra, field_lines = schema["Config"]
        known_top = top_fields | top_extra | set(SECTION_ATTRS)
        for attr, rel, line, col, qual in self.top_reads:
            if attr.startswith("_") or attr in known_top:
                continue
            out.append(Violation(
                rule=self.id, path=rel, line=line, col=col,
                message=f"config key `{attr}` read here but not a "
                        "Config field in utils/config.py (typo or "
                        "missing default)", context=qual))
        for section, attr, rel, line, col, qual in self.section_reads:
            fields, extra, _ = schema[SECTION_ATTRS[section]]
            if attr.startswith("_") or attr in fields | extra:
                continue
            out.append(Violation(
                rule=self.id, path=rel, line=line, col=col,
                message=f"config key `{section}.{attr}` read here but "
                        f"not a {SECTION_ATTRS[section]} field in "
                        "utils/config.py", context=qual))
        # reverse direction: dead defaults
        read_top = {a for a, *_ in self.top_reads}
        read_sec = {(s, a) for s, a, *_ in self.section_reads}
        for cls, prefix in [("Config", "")] + [
                (c, s + ".") for s, c in SECTION_ATTRS.items()]:
            fields, _, lines = schema[cls]
            for f in sorted(fields):
                used = (f in read_top if not prefix
                        else (prefix[:-1], f) in read_sec)
                if used or f in self.string_constants \
                        or re.search(rf"\b{re.escape(f)}\b", readme):
                    continue
                out.append(Violation(
                    rule=self.id, path=self.config_ctx.rel_path,
                    line=lines.get(f, 1), col=0,
                    message=f"config default `{prefix}{f}` is never "
                            "read in code, as a string constant, or "
                            "documented in README (dead knob?)",
                    context=cls))
        return out


# ---- GL09 --------------------------------------------------------------

# request-plane packages where module-level mutable state is
# process-local but SEMANTICALLY node-wide: with the multi-process
# gateway, N workers each get their own copy of such state, so counters
# silently read 1/N, caches duplicate, and limits admit N× (exactly the
# bug class ISSUE 8 creates). Node-wide state must live on an instance
# wired through Garage (one per process, aggregated by the supervisor)
# or be brokered (the qos lease protocol).
CROSS_WORKER_DIRS = ("api/", "qos/", "gateway/", "web/")

MUTATING_METHODS = {"append", "extend", "add", "update", "pop",
                    "popitem", "clear", "remove", "discard",
                    "setdefault", "insert", "appendleft", "__setitem__"}

MUTABLE_CONSTRUCTORS = {"dict", "list", "set", "OrderedDict",
                        "defaultdict", "deque", "Counter", "bytearray"}


class CrossWorkerState(Rule):
    id = "GL09"
    name = "cross-worker-state"
    summary = ("module-level mutable state in a request-plane package "
               "mutated from function scope: process-local but "
               "semantically node-wide — each gateway worker gets its "
               "own copy (counters read 1/N, limits admit N×)")
    rationale = (
        "Under the multi-process gateway (PR 8) every worker holds "
        "its own copy of module-level state in api/ qos/ gateway/ "
        "web/ — counters silently read 1/N, caches duplicate, limits "
        "admit N×. Node-wide state belongs on instances wired "
        "through Garage (aggregated by the supervisor) or leased via "
        "the broker. Read-only lookup tables and import-time "
        "construction are exempt.")
    example_fire = ("PENDING = {}\n"
                    "async def handle(req):\n"
                    "    PENDING[req.id] = req   # per-worker copy")
    example_ok = ("STATUS = {200: 'OK'}          # read-only table\n"
                  "def reason(code):\n"
                  "    return STATUS.get(code)")

    def applies_to(self, ctx: FileContext) -> bool:
        if ctx.is_test:
            return False
        rel = ctx.rel_path
        for d in CROSS_WORKER_DIRS:
            if f"garage_tpu/{d}" in rel or rel.startswith(d):
                return True
        return False

    def finish_file(self, ctx: FileContext) -> None:
        # 1) module-level names bound to mutable containers
        mutable: dict[str, ast.AST] = {}
        for stmt in ctx.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets
                           if isinstance(t, ast.Name)]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name) \
                    and stmt.value is not None:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            is_mut = isinstance(value, (ast.Dict, ast.List, ast.Set)) \
                or (isinstance(value, ast.Call)
                    and call_name(value) in MUTABLE_CONSTRUCTORS)
            if not is_mut:
                continue
            for t in targets:
                mutable[t.id] = stmt
        if not mutable:
            return
        # 2) ... that any function in the module mutates. Module-level
        # init-time mutation (building a constant table at import) is
        # fine; mutation from function scope is cross-request state.
        flagged: set[str] = set()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            for sub in _walk_own_scope(fn):
                name = None
                if isinstance(sub, (ast.Assign, ast.AugAssign,
                                    ast.Delete)):
                    tgts = (sub.targets
                            if isinstance(sub, (ast.Assign, ast.Delete))
                            else [sub.target])
                    for t in tgts:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name):
                            name = t.value.id
                elif isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in MUTATING_METHODS \
                        and isinstance(sub.func.value, ast.Name):
                    name = sub.func.value.id
                if name in mutable and name not in flagged \
                        and name not in _locally_bound(fn, name):
                    flagged.add(name)
                    ctx.report(
                        self.id, mutable[name],
                        f"module-level mutable `{name}` is mutated "
                        f"from `{fn.name}`: process-local state that "
                        "reads as node-wide — under the multi-process "
                        "gateway every worker holds its own copy. "
                        "Move it onto an instance wired through "
                        "Garage, or lease/aggregate it via gateway/")

    def finish_project(self, project: ProjectState) -> list[Violation]:
        return []


def _walk_own_scope(fn: ast.AST):
    """Walk fn's body WITHOUT descending into nested def/lambda scopes
    — a nested function's locals and mutations belong to the nested
    function's own check, and letting them leak into the enclosing
    scope both hides real module-state mutations (a nested
    `NAME = {}` would shadow NAME for the whole outer body) and
    invents false ones."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _locally_bound(fn: ast.AST, name: str) -> set[str]:
    """Names shadowed inside fn (params or direct assignment) — a local
    `queues = {}` mutated in the same function is not module state. An
    explicit `global` declaration un-shadows: that IS module state.
    Nested def/lambda scopes are excluded (their locals are theirs)."""
    bound: set[str] = set()
    declared_global: set[str] = set()
    args = fn.args
    for a in (args.args + args.kwonlyargs + args.posonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        bound.add(a.arg)
    for sub in _walk_own_scope(fn):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    bound.add(t.id)
        elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)) \
                and isinstance(sub.target, ast.Name):
            bound.add(sub.target.id)
        elif isinstance(sub, ast.Global):
            declared_global |= set(sub.names)
    bound -= declared_global
    return bound if name in bound else set()


def _parse_config_schema(tree: ast.Module) -> dict:
    """Per config class: ({fields}, {properties+methods}, {field: line})
    straight from the dataclass AST."""
    out = {c: (set(), set(), {}) for c in CONFIG_CLASSES}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name in CONFIG_CLASSES):
            continue
        fields, extra, lines = out[node.name]
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                fields.add(stmt.target.id)
                lines[stmt.target.id] = stmt.lineno
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                extra.add(stmt.name)
    return out
