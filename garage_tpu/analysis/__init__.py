"""garage-lint: project-invariant static analysis (stdlib-ast only).

Run it:  python -m garage_tpu.analysis [--format json|text] [paths]
         python -m garage_tpu.analysis --explain GL10
         python -m garage_tpu.analysis --fix-waivers [--write]

Rules (each encodes an invariant an earlier PR established by hand):

  GL01 blocking-call-in-async   blocking I/O / digest-of-data on the
                                event loop (PR 2's fast-path class)
  GL02 hedge-on-mutation        hedged or hedge-defaulting RPC on a
                                write endpoint (PR 4's k2v pin); since
                                ISSUE 9 strategies passed across
                                function boundaries resolve too
  GL03 ssec-cache-leak          SSE-C taint reaching the block cache
                                seam without explicit cacheable= —
                                true taint tracking across helper
                                boundaries since ISSUE 9
  GL04 orphan-task              create_task/ensure_future result dropped
  GL05 swallowed-exception      except Exception: pass (Aspirator)
  GL06 await-holding-lock       RPC awaited inside `with lock:` /
                                `async with lock:` (sync locks count
                                since ISSUE 9)
  GL07 unregistered-metric      dynamic / off-scheme metric names
  GL08 config-knob-drift        code<->utils/config.py key drift
  GL09 cross-worker-state       module-level mutable state in the
                                request plane (api/ qos/ gateway/ web/)
                                mutated from function scope
  GL10 blocking-reachable-from-async
                                a sync helper that blocks, called
                                transitively from an async def with no
                                to_thread hop (reports the full chain);
                                since ISSUE 14 the db seam is the
                                @blocking_api annotation where the
                                call resolves, and iterating an
                                in-project generator counts
  GL11 leaked-budget-on-exception
                                qos token/lease/semaphore acquire whose
                                refund/release is not on every exit
                                path — cross-function since ISSUE 14
                                (acquire here / release in a callee
                                settles through the call graph)
  GL12 await-interleaving-atomicity
                                read -> await -> write on the same
                                shared lvalue with no lock across the
                                await (check-then-act race; ISSUE 14)
  GL13 lock-order-inversion     lock-acquisition cycles across the
                                global graph — the ABBA deadlock, both
                                chains reported (ISSUE 14); lock
                                identity is allocation-site-aware
                                since ISSUE 20
  GL14 jit-cache-key-leak       a jit/compile cache keyed on data-
                                dependent pattern values (PR 11's
                                per-pattern program leak); device path
                                only (block/ ops/ parallel/)
  GL15 unpadded-device-launch   device_put / batched-kernel operand
                                sized from raw len()/max() instead of
                                the pad_buckets ladder; device path
                                only
  GL16 loop-touch-from-stage-thread
                                stage-executor-executed code reaching
                                loop-affine asyncio primitives without
                                the *_threadsafe crossings; device
                                path only
  GL00 (framework)              stale waivers, stale baseline entries,
                                unparseable files — cannot be waived

GL02/GL03/GL10-GL13 and GL16 run on the two-pass interprocedural
engine (dataflow.py summaries + callgraph.py resolution — see README
"How dataflow resolution works"). Since ISSUE 20 the summaries carry
an explicit per-function CFG (path-sensitive GL11, loop-carried GL12),
allocation-site lock identity (per-instance GL13), and receiver type
facts that rank above unique-method CHA in call resolution. The runtime half is
utils/sanitizer.py (GARAGE_SANITIZE=1): loop-stall detection +
teardown leak/conservation checks wired into tests/conftest.py.

Waive a deliberate site inline, with a reason (checked for staleness):

    risky()  # lint: ignore[GL05] abort path; partial state dropped
"""

from __future__ import annotations

from .baseline import (DEFAULT_BASELINE, apply_baseline, load_baseline,
                       save_baseline)
from .callgraph import CallGraph
from .core import META_RULE, FileContext, ProjectState, Rule, Violation
from .dataflow import (DataflowState, summarize_tree, summary_fingerprint,
                       summary_json)
from .rules_async import (AwaitHoldingLock, BlockingCallInAsync,
                          OrphanTask, SwallowedException)
from .rules_concurrency import (AwaitInterleavingAtomicity,
                                LockOrderInversion)
from .rules_dataflow import (BlockingReachableFromAsync,
                             LeakedBudgetOnException)
from .rules_device import (JitCacheKeyLeak, LoopTouchFromStageThread,
                           UnpaddedDeviceLaunch)
from .rules_project import (ConfigKnobDrift, CrossWorkerState,
                            UnregisteredMetric)
from .rules_rpc import HedgeOnMutation, SsecCacheLeak
from .walker import analyze_paths, analyze_source

RULE_CLASSES = [
    BlockingCallInAsync,        # GL01
    HedgeOnMutation,            # GL02
    SsecCacheLeak,              # GL03
    OrphanTask,                 # GL04
    SwallowedException,         # GL05
    AwaitHoldingLock,           # GL06
    UnregisteredMetric,         # GL07
    ConfigKnobDrift,            # GL08
    CrossWorkerState,           # GL09
    BlockingReachableFromAsync,  # GL10
    LeakedBudgetOnException,    # GL11
    AwaitInterleavingAtomicity,  # GL12
    LockOrderInversion,         # GL13
    JitCacheKeyLeak,            # GL14
    UnpaddedDeviceLaunch,       # GL15
    LoopTouchFromStageThread,   # GL16
]


def default_rules() -> list[Rule]:
    """Fresh rule instances (cross-file rules carry per-run state)."""
    return [cls() for cls in RULE_CLASSES]


__all__ = [
    "analyze_paths", "analyze_source", "default_rules", "RULE_CLASSES",
    "Violation", "Rule", "FileContext", "ProjectState", "META_RULE",
    "DEFAULT_BASELINE", "load_baseline", "save_baseline",
    "apply_baseline", "CallGraph", "DataflowState", "summarize_tree",
    "summary_fingerprint", "summary_json",
]
