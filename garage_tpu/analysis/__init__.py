"""garage-lint: project-invariant static analysis (stdlib-ast only).

Run it:  python -m garage_tpu.analysis [--format json|text] [paths]

Rules (each encodes an invariant an earlier PR established by hand):

  GL01 blocking-call-in-async   blocking I/O / digest-of-data on the
                                event loop (PR 2's fast-path class)
  GL02 hedge-on-mutation        hedged or hedge-defaulting RPC on a
                                write endpoint (PR 4's k2v pin)
  GL03 ssec-cache-leak          SSE-C scope reaching the block cache
                                seam without explicit cacheable=
  GL04 orphan-task              create_task/ensure_future result dropped
  GL05 swallowed-exception      except Exception: pass (Aspirator)
  GL06 await-holding-lock       RPC awaited inside `async with lock:`
  GL07 unregistered-metric      dynamic / off-scheme metric names
  GL08 config-knob-drift        code<->utils/config.py key drift
  GL09 cross-worker-state       module-level mutable state in the
                                request plane (api/ qos/ gateway/ web/)
                                mutated from function scope — process-
                                local but semantically node-wide (the
                                multi-process gateway's bug class)
  GL00 (framework)              stale waivers, stale baseline entries,
                                unparseable files — cannot be waived

Waive a deliberate site inline, with a reason (checked for staleness):

    risky()  # lint: ignore[GL05] abort path; partial state dropped
"""

from __future__ import annotations

from .baseline import (DEFAULT_BASELINE, apply_baseline, load_baseline,
                       save_baseline)
from .core import META_RULE, FileContext, ProjectState, Rule, Violation
from .rules_async import (AwaitHoldingLock, BlockingCallInAsync,
                          OrphanTask, SwallowedException)
from .rules_project import (ConfigKnobDrift, CrossWorkerState,
                            UnregisteredMetric)
from .rules_rpc import HedgeOnMutation, SsecCacheLeak
from .walker import analyze_paths, analyze_source

RULE_CLASSES = [
    BlockingCallInAsync,   # GL01
    HedgeOnMutation,       # GL02
    SsecCacheLeak,         # GL03
    OrphanTask,            # GL04
    SwallowedException,    # GL05
    AwaitHoldingLock,      # GL06
    UnregisteredMetric,    # GL07
    ConfigKnobDrift,       # GL08
    CrossWorkerState,      # GL09
]


def default_rules() -> list[Rule]:
    """Fresh rule instances (cross-file rules carry per-run state)."""
    return [cls() for cls in RULE_CLASSES]


__all__ = [
    "analyze_paths", "analyze_source", "default_rules", "RULE_CLASSES",
    "Violation", "Rule", "FileContext", "ProjectState", "META_RULE",
    "DEFAULT_BASELINE", "load_baseline", "save_baseline",
    "apply_baseline",
]
