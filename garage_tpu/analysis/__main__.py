"""CLI: python -m garage_tpu.analysis [--format json|text|sarif] [paths]

Exit codes: 0 clean (waived/baselined findings allowed), 1 active
violations, 2 bad invocation. CI's lint job is exactly
`python -m garage_tpu.analysis` (text output feeds the GitHub problem
matcher; `--format json` is the machine surface, `--format sarif`
emits a minimal SARIF 2.1.0 log for code-scanning upload).

Extras (ISSUE 9):
  --explain RULE        rule rationale + a firing and a suppressed
                        example, straight from the rule class
  --fix-waivers         delete stale `# lint: ignore[...]` comments
                        GL00 flags (dry-run by default; --write
                        applies); a multi-rule waiver where only SOME
                        rules are stale keeps the surviving rules
  --summary-cache PATH  reuse pass-1 dataflow summaries for files whose
                        sha256 is unchanged (CI keys the cache on the
                        tree hash; a miss just re-summarizes)
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

from . import (DEFAULT_BASELINE, META_RULE, analyze_paths,
               apply_baseline, default_rules, load_baseline,
               save_baseline)
from .core import WAIVER_RE

# harness files included in the default scan with the scoped
# GL04/GL05/GL07 subset (walker.HARNESS_RULES)
HARNESS_DEFAULTS = ("tests/clusterbox.py", "tests/conftest.py",
                    "bench.py")

GL00_EXPLAIN = {
    "rationale": (
        "The framework's own hygiene: a waiver that suppresses nothing, "
        "carries no reason, or names GL00 itself; a baseline entry that "
        "matches nothing; an unparseable file. Suppressions must not "
        "rot silently, so GL00 cannot be waived."),
    "example_fire": 'def f():  # lint: ignore[GL05] nothing fires here\n'
                    '    return 1',
    "example_ok": 'risky()  # lint: ignore[GL05] best-effort telemetry',
}


def _repo_root() -> str:
    # garage_tpu/analysis/__main__.py -> repo root two levels above
    # the package
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _explain(rule_id: str) -> int:
    rule_id = rule_id.strip().upper()
    if rule_id == META_RULE:
        info, name, summary = GL00_EXPLAIN, "(framework)", \
            "waiver/baseline hygiene"
    else:
        match = [r for r in default_rules() if r.id == rule_id]
        if not match:
            print(f"no such rule: {rule_id}", file=sys.stderr)
            return 2
        r = match[0]
        name, summary = r.name, r.summary
        info = {
            "rationale": getattr(r, "rationale", "") or r.summary,
            "example_fire": getattr(r, "example_fire", ""),
            "example_ok": getattr(r, "example_ok", ""),
        }
    print(f"{rule_id} {name}\n")
    print(f"  {summary}\n")
    print("rationale:")
    for line in info["rationale"].splitlines():
        print(f"  {line.strip()}" if line.strip() else "")
    if info["example_fire"]:
        print("\nfires on:\n")
        for line in info["example_fire"].splitlines():
            print(f"    {line}")
    if info["example_ok"]:
        print("\nquiet on:\n")
        for line in info["example_ok"].splitlines():
            print(f"    {line}")
    return 0


# GL00's per-rule staleness message names exactly the stale ids:
# "stale waiver for GL05,GL07: suppresses nothing ..."
_STALE_MSG_RE = re.compile(r"stale waiver for ([A-Z0-9,]+):")


def _fix_waivers(paths: list[str], root: str, write: bool) -> int:
    """Delete waiver comments GL00 reports as stale. Dry-run prints
    the edits; --write applies them. A multi-rule waiver where only
    some rules are stale is REWRITTEN to keep the surviving rules and
    the reason; the whole comment is removed only when every rule it
    names is stale, and a line that becomes empty is dropped."""
    rules = default_rules()
    violations, project = analyze_paths(paths, rules, root=root,
                                        data=_readme_data(root))
    stale: dict[str, dict[int, set[str]]] = {}
    for v in violations:
        if v.rule == META_RULE and "stale waiver" in v.message:
            m = _STALE_MSG_RE.search(v.message)
            ids = set(m.group(1).split(",")) if m else set()
            stale.setdefault(v.path, {}).setdefault(v.line,
                                                    set()).update(ids)
    if not stale:
        print("no stale waivers")
        return 0
    edits = 0
    for rel, lines in sorted(stale.items()):
        ap = os.path.join(root, rel)
        try:
            with open(ap, "r", encoding="utf-8") as f:
                src_lines = f.read().splitlines(keepends=True)
        except OSError as e:
            print(f"{rel}: unreadable ({e})", file=sys.stderr)
            continue
        for ln in sorted(lines, reverse=True):
            if ln - 1 >= len(src_lines):
                continue
            line = src_lines[ln - 1]
            nl = "\n" if line.endswith("\n") else ""
            wm = WAIVER_RE.search(line)
            keep: list[str] = []
            if wm and lines[ln]:
                named = [t.strip().upper()
                         for t in wm.group(1).split(",")]
                keep = [r for r in named if r and r not in lines[ln]]
            if keep:
                reason = wm.group(2).strip()
                comment = f"# lint: ignore[{','.join(keep)}]"
                if reason:
                    comment += f" {reason}"
                new_line = line[:wm.start()] + comment
                print(f"{rel}:{ln}: keep {','.join(keep)}: "
                      f"{line.rstrip()}")
                if write:
                    src_lines[ln - 1] = new_line + nl
            else:
                stripped = WAIVER_RE.sub("", line).rstrip()
                action = ("drop line" if not stripped.strip()
                          else "strip comment")
                print(f"{rel}:{ln}: {action}: {line.rstrip()}")
                if write:
                    if stripped.strip():
                        src_lines[ln - 1] = stripped + nl
                    else:
                        del src_lines[ln - 1]
            edits += 1
        if write:
            with open(ap, "w", encoding="utf-8") as f:
                f.write("".join(src_lines))
    verb = ("rewritten/removed" if write
            else "would rewrite/remove (dry-run; pass --write)")
    print(f"{edits} stale waiver(s) {verb}")
    return 0


def _to_sarif(active, rules) -> dict:
    """Minimal SARIF 2.1.0 log: one run, the rule table in
    tool.driver.rules, one result per active violation."""
    rule_meta = [{"id": r.id, "name": r.name,
                  "shortDescription": {"text": r.summary}}
                 for r in rules]
    rule_meta.append({"id": META_RULE, "name": "framework",
                      "shortDescription":
                          {"text": "waiver/baseline hygiene"}})
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "garage-lint",
                                "rules": rule_meta}},
            "results": [{
                "ruleId": v.rule,
                "level": "error",
                "message": {"text": v.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": v.path},
                    "region": {"startLine": v.line,
                               "startColumn": max(v.col, 0) + 1},
                }}],
            } for v in active],
        }],
    }


def _readme_data(root: str) -> dict:
    # GL08's reverse direction accepts README documentation as a knob's
    # reason to exist
    data = {}
    readme = os.path.join(root, "README.md")
    if os.path.exists(readme):
        with open(readme, "r", encoding="utf-8") as f:
            data["readme_text"] = f.read()
    return data


def _load_summary_cache(path: str) -> dict:
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        return raw if isinstance(raw, dict) else {}
    except (OSError, ValueError):
        return {}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m garage_tpu.analysis",
        description="garage-lint: project-invariant static analysis")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to scan (default: the "
                             "garage_tpu package + harness files)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path, or 'none' "
                             f"(default: <repo>/{DEFAULT_BASELINE})")
    parser.add_argument("--write-baseline", action="store_true",
                        help="snapshot current active violations into "
                             "the baseline file and exit 0")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--explain", metavar="RULE", default=None,
                        help="print a rule's rationale + fire/suppress "
                             "examples and exit")
    parser.add_argument("--fix-waivers", action="store_true",
                        help="delete stale waiver comments (dry-run "
                             "unless --write)")
    parser.add_argument("--write", action="store_true",
                        help="apply --fix-waivers edits in place")
    parser.add_argument("--summary-cache", default=None, metavar="PATH",
                        help="pass-1 summary cache JSON, keyed on file "
                             "sha256 (read + rewritten each run)")
    args = parser.parse_args(argv)

    if args.explain:
        return _explain(args.explain)

    root = _repo_root()
    paths = args.paths or [os.path.join(root, "garage_tpu")] + [
        p for p in (os.path.join(root, h) for h in HARNESS_DEFAULTS)
        if os.path.exists(p)]

    if args.fix_waivers:
        return _fix_waivers(paths, root, args.write)

    rules = default_rules()
    if args.rules:
        want = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in rules if r.id in want]
        if not rules:
            print(f"no such rules: {args.rules}", file=sys.stderr)
            return 2

    data = _readme_data(root)
    if args.summary_cache:
        data["summary_cache"] = _load_summary_cache(args.summary_cache)

    t0 = time.monotonic()
    violations, project = analyze_paths(paths, rules, root=root,
                                        data=data,
                                        restricted=bool(args.rules))
    elapsed = time.monotonic() - t0

    if args.summary_cache and "_dataflow" in project.data:
        df = project.data["_dataflow"]
        tmp = args.summary_cache + ".tmp"
        os.makedirs(os.path.dirname(os.path.abspath(args.summary_cache)),
                    exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(df.cache_payload(), f, sort_keys=True,
                      separators=(",", ":"))
        os.replace(tmp, args.summary_cache)

    baseline_path = args.baseline
    if baseline_path != "none":
        baseline_path = baseline_path or os.path.join(root,
                                                      DEFAULT_BASELINE)
        if args.write_baseline:
            n = save_baseline(baseline_path, violations)
            print(f"wrote {n} baseline entries to {baseline_path}")
            return 0
        violations.extend(apply_baseline(violations,
                                         load_baseline(baseline_path)))
        violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))

    active = [v for v in violations if v.active]
    if args.format == "sarif":
        print(json.dumps(_to_sarif(active, rules), indent=2,
                         sort_keys=True))
    elif args.format == "json":
        df = project.data.get("_dataflow")
        print(json.dumps({
            "violations": [v.to_dict() for v in active],
            "waived": sum(1 for v in violations if v.waived),
            "baselined": sum(1 for v in violations if v.baselined),
            "files": len(project.files),
            "elapsed_s": round(elapsed, 3),
            "summary_cache_hits": df.cache_hits if df else 0,
        }, indent=2))
    else:
        for v in active:
            print(v.render())
        waived = sum(1 for v in violations if v.waived)
        base = sum(1 for v in violations if v.baselined)
        print(f"{len(project.files)} files, {len(active)} violations "
              f"({waived} waived, {base} baselined) in {elapsed:.1f}s")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
