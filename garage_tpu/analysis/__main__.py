"""CLI: python -m garage_tpu.analysis [--format json|text] [paths]

Exit codes: 0 clean (waived/baselined findings allowed), 1 active
violations, 2 bad invocation. CI's lint job is exactly
`python -m garage_tpu.analysis --format json`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (DEFAULT_BASELINE, META_RULE, analyze_paths,
               apply_baseline, default_rules, load_baseline,
               save_baseline)


def _repo_root() -> str:
    # garage_tpu/analysis/__main__.py -> repo root two levels above
    # the package
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m garage_tpu.analysis",
        description="garage-lint: project-invariant static analysis")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to scan (default: the "
                             "garage_tpu package)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path, or 'none' "
                             f"(default: <repo>/{DEFAULT_BASELINE})")
    parser.add_argument("--write-baseline", action="store_true",
                        help="snapshot current active violations into "
                             "the baseline file and exit 0")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    args = parser.parse_args(argv)

    root = _repo_root()
    paths = args.paths or [os.path.join(root, "garage_tpu")]
    rules = default_rules()
    if args.rules:
        want = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in rules if r.id in want]
        if not rules:
            print(f"no such rules: {args.rules}", file=sys.stderr)
            return 2

    # GL08's reverse direction accepts README documentation as a knob's
    # reason to exist
    data = {}
    readme = os.path.join(root, "README.md")
    if os.path.exists(readme):
        with open(readme, "r", encoding="utf-8") as f:
            data["readme_text"] = f.read()

    violations, project = analyze_paths(paths, rules, root=root,
                                        data=data)

    baseline_path = args.baseline
    if baseline_path != "none":
        baseline_path = baseline_path or os.path.join(root,
                                                      DEFAULT_BASELINE)
        if args.write_baseline:
            n = save_baseline(baseline_path, violations)
            print(f"wrote {n} baseline entries to {baseline_path}")
            return 0
        violations.extend(apply_baseline(violations,
                                         load_baseline(baseline_path)))
        violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))

    active = [v for v in violations if v.active]
    if args.format == "json":
        print(json.dumps({
            "violations": [v.to_dict() for v in active],
            "waived": sum(1 for v in violations if v.waived),
            "baselined": sum(1 for v in violations if v.baselined),
            "files": len(project.files),
        }, indent=2))
    else:
        for v in active:
            print(v.render())
        waived = sum(1 for v in violations if v.waived)
        base = sum(1 for v in violations if v.baselined)
        print(f"{len(project.files)} files, {len(active)} violations "
              f"({waived} waived, {base} baselined)")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
