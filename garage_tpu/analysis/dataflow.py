"""Pass 1 of the interprocedural engine: per-function summaries.

Infer-style compositional analysis (ISSUE 9): one extra walk per file
extracts, for every function, the facts the flow rules need —

  * name-level taint: which PARAMS flow into each call argument and
    into the return value (two monotone passes over the body, enough
    for the straight-line helper chains the rules care about);
  * SSE-C sources in scope (sse-named params/locals, decrypt results);
  * blocking atoms: calls that pin the event loop if reached from an
    `async def` without a thread hop — GL01's I/O list plus the
    project's sync db seams (`self.store.iter`, `db.transaction`, ...);
  * call records with enough structure to build the project call graph
    (callgraph.py): self/name/dotted/attr refs, `asyncio.to_thread` /
    `functools.partial` / `run_in_executor` unwrapping, awaited-ness,
    kwarg names, RequestStrategy argument classification;
  * resource discipline: qos/lease/semaphore acquires and whether their
    refund/release is structurally on every exit path (GL11's fact);
  * since ISSUE 20: an explicit per-function CFG (build_cfg — branch/
    loop/try-except/return edges, back-edges marked) for path-sensitive
    pass-2 queries, allocation-site lock identity, and receiver typing
    facts (var_types) that rank above unique-method CHA in pass 2.

Summaries are plain dicts of sorted primitives: `json.dumps(...,
sort_keys=True)` over the same tree is byte-identical, which is what
lets CI cache pass 1 keyed on file hash (`--summary-cache`).
"""

from __future__ import annotations

import ast
import hashlib
import json
from typing import Optional

from .rules_async import BLOCKING_CALLS as _GL01_BLOCKING

from .core import (MUTATION_NAME_RE, MUTATION_OP_RE, chain_segments,
                   dotted_name, payload_ops)

# ---- blocking atoms -----------------------------------------------------

# GL01's hard-I/O list IS the base (imported, not copied — the direct
# and transitive rules must never disagree about what blocks); GL10
# additionally treats durable-rename/fsync syscalls as atoms because
# they hide inside sync persistence helpers. Digest helpers are
# deliberately NOT propagated transitively: hashing a 32-byte key two
# frames down is microseconds, and GL01 already flags digest-of-data
# DIRECTLY in an async frame where the payload is plausibly large.

IO_BLOCKING_CALLS = _GL01_BLOCKING | {
    "os.fsync", "os.replace", "os.rename",
}

# the project's synchronous metadata seams: a non-awaited method call
# on a db-ish receiver is a sqlite/LSM operation that belongs in a
# worker thread when reachable from the event loop (db.py convention
# since PR 1). Receiver segment must MATCH (not merely contain) one of
# these so `self.store.iter(...)`, `db.transaction(...)`,
# `self.merkle_todo.insert(...)` qualify but e.g. `self.restore.get`
# does not.
DB_RECEIVER_RE = r"(^|_)(store|db|tree|todo|queue|timestamp)$"
DB_METHODS = {"get", "iter", "insert", "remove", "transaction",
              "open_tree", "snapshot", "checkpoint"}

THREAD_HOPS = {"to_thread", "run_in_executor"}

SSE_NAME_RE = r"(^|_)sse"
DECRYPT_RE = r"(^|_)(decrypt|unseal)"

ACQUIRE_METHODS = {"acquire", "try_acquire"}
RELEASE_METHODS = {"release", "refund", "give_back", "revoke"}

# mutating container/method calls on a tracked shared lvalue count as
# WRITES for the GL12 interleaving analysis (the receiver read they
# imply is part of the atomic mutation, not a separate stale check, so
# no read event is emitted for it)
MUT_METHODS = {"add", "append", "appendleft", "clear", "discard",
               "extend", "insert", "pop", "popleft", "popitem",
               "remove", "setdefault", "update"}
# accretive subset: these operate on the LIVE state at mutation time
# (append/add/insert can't clobber a concurrent task's entry,
# setdefault is itself an atomic re-check), so they count as a
# re-validating read immediately before their write — a stale
# pre-await check cannot make them lose another task's update
ACCRETIVE_METHODS = {"add", "append", "appendleft", "extend", "insert",
                     "setdefault"}

# identifier segment that marks a context-manager expression as a lock
LOCK_SEG = "lock"

import re as _re

_DB_RECEIVER = _re.compile(DB_RECEIVER_RE)
_SSE_NAME = _re.compile(SSE_NAME_RE, _re.IGNORECASE)
_DECRYPT = _re.compile(DECRYPT_RE, _re.IGNORECASE)


# bump on ANY change to the summary schema or extraction semantics —
# cached entries from other versions are recomputed, not trusted
# (v3: ISSUE 14 — exit-path contexts on call/acquire/release records,
# shared-state access events, lock-acquisition facts, generator-
# iteration flags, blocking_api annotations)
# (v4: ISSUE 20 — explicit per-function CFG with back-edges, loop
# back-edge unrolling in the concurrency event stream, allocation-site
# lock identity, receiver type facts for import-aware call resolution)
SUMMARY_VERSION = 4


def module_name_of(rel_path: str) -> str:
    """garage_tpu/model/k2v/rpc.py -> garage_tpu.model.k2v.rpc"""
    p = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    p = p.replace("\\", "/")
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def _call_ref(func_expr: ast.AST) -> Optional[list]:
    """Reference shape for a callable expression:
       ["name", n]          bare name
       ["self", m]          self.m / cls.m
       ["dotted", "a.b.c"]  attribute chain rooted at a plain name
       ["attr", m]          method on an arbitrary expression
    Receiver segments ride separately in the call record."""
    segs = chain_segments(func_expr)
    if not segs:
        return None
    if len(segs) == 1:
        return ["name", segs[0]]
    if segs[0] in ("self", "cls"):
        if len(segs) == 2:
            return ["self", segs[1]]
        return ["attr", segs[-1]]
    dn = dotted_name(func_expr)
    if dn is not None:
        return ["dotted", dn]
    return ["attr", segs[-1]]


def _payload_ops(node: ast.Call) -> list[str]:
    return sorted(set(payload_ops(node)))


def _contains_await(node: ast.AST) -> bool:
    """An Await in THIS frame (nested defs excluded; lambdas cannot
    contain await)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n is not node:
            continue
        if isinstance(n, ast.Await):
            return True
        stack.extend(ast.iter_child_nodes(n))
    return False


# ---- control-flow graph (ISSUE 20) -------------------------------------

def build_cfg(fn_node: ast.AST) -> dict:
    """Explicit statement-level control-flow graph for one function:
    blocks of consecutive simple statements, edges for branch / loop /
    try-except / return flow, loop back-edges marked. Block 0 is the
    entry; ``-1`` is the virtual exit. Each block records the source
    lines of its statements plus the lines of call expressions inside
    them (nested defs/lambdas excluded), which lets pass-2 rules ask
    "is this call on some CFG path between these two lines" instead of
    "is it textually between them" (GL11's risky-call check).

    Approximations, each sound for lint (they only ADD paths, never
    hide one): exception edges enter a handler only from body blocks
    that can raise (a call, an explicit raise, an await/yield, or an
    assert) — a handler guarding a raise-free body is unreachable; a
    nested raise links to every enclosing handler level, not just the
    innermost; a return inside try/finally jumps straight to the exit
    without threading the finally body."""
    blocks: list[dict] = []

    def new_block() -> dict:
        b = {"id": len(blocks), "lines": [], "calls": [],
             "succ": [], "back": [], "_raises": False}
        blocks.append(b)
        return b

    def link(a: dict, b: dict, back: bool = False) -> None:
        if b["id"] not in a["succ"]:
            a["succ"].append(b["id"])
        if back and b["id"] not in a["back"]:
            a["back"].append(b["id"])

    def to_exit(a: dict) -> None:
        if -1 not in a["succ"]:
            a["succ"].append(-1)

    def note_calls(b: dict, node: ast.AST) -> None:
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not node:
                continue  # nested scope: its calls are not this frame's
            if isinstance(n, ast.Call):
                b["calls"].append(n.lineno)
                b["_raises"] = True
            elif isinstance(n, (ast.Raise, ast.Await, ast.Yield,
                                ast.YieldFrom, ast.Assert)):
                b["_raises"] = True
            stack.extend(ast.iter_child_nodes(n))

    def note(b: dict, st: ast.AST) -> None:
        b["lines"].append(st.lineno)
        note_calls(b, st)

    def flow(stmts: list, cur, loops: list, handlers: list):
        """Thread `stmts` through the graph starting in block `cur`;
        returns the open fall-through block, or None when control
        cannot reach past the last statement."""
        for st in stmts:
            if cur is None:
                cur = new_block()  # unreachable tail, still modeled
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                cur["lines"].append(st.lineno)
            elif isinstance(st, ast.Return):
                note(cur, st)
                to_exit(cur)
                cur = None
            elif isinstance(st, ast.Raise):
                note(cur, st)
                if handlers:
                    for h in handlers[-1]:
                        link(cur, h)
                else:
                    to_exit(cur)
                cur = None
            elif isinstance(st, ast.Break):
                cur["lines"].append(st.lineno)
                if loops:
                    link(cur, loops[-1][1])
                cur = None
            elif isinstance(st, ast.Continue):
                cur["lines"].append(st.lineno)
                if loops:
                    link(cur, loops[-1][0], back=True)
                cur = None
            elif isinstance(st, ast.If):
                cur["lines"].append(st.lineno)
                note_calls(cur, st.test)
                then_b = new_block()
                link(cur, then_b)
                out_t = flow(st.body, then_b, loops, handlers)
                if st.orelse:
                    else_b = new_block()
                    link(cur, else_b)
                    out_e = flow(st.orelse, else_b, loops, handlers)
                else:
                    out_e = cur
                outs = [o for o in (out_t, out_e) if o is not None]
                if outs:
                    join = new_block()
                    for o in outs:
                        link(o, join)
                    cur = join
                else:
                    cur = None
            elif isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
                header = new_block()
                link(cur, header)
                header["lines"].append(st.lineno)
                note_calls(header, st.test if isinstance(st, ast.While)
                           else st.iter)
                after = new_block()
                body_b = new_block()
                link(header, body_b)
                out_b = flow(st.body, body_b,
                             loops + [(header, after)], handlers)
                if out_b is not None:
                    link(out_b, header, back=True)
                if st.orelse:
                    oe = new_block()
                    link(header, oe)
                    out_oe = flow(st.orelse, oe, loops, handlers)
                    if out_oe is not None:
                        link(out_oe, after)
                else:
                    link(header, after)
                cur = after
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                cur["lines"].append(st.lineno)
                for item in st.items:
                    note_calls(cur, item.context_expr)
                cur = flow(st.body, cur, loops, handlers)
            elif isinstance(st, ast.Try):
                h_blks = [new_block() for _ in st.handlers]
                body_b = new_block()
                link(cur, body_b)
                lo = body_b["id"]
                out_body = flow(st.body, body_b, loops,
                                handlers + ([h_blks] if h_blks else []))
                hi = len(blocks)
                if h_blks:
                    h_ids = {h["id"] for h in h_blks}
                    for b in blocks[lo:hi]:
                        if b["_raises"] and b["id"] not in h_ids:
                            for h in h_blks:
                                link(b, h)
                if st.orelse and out_body is not None:
                    out_body = flow(st.orelse, out_body, loops, handlers)
                outs = [out_body] if out_body is not None else []
                for h, hb in zip(st.handlers, h_blks):
                    hb["lines"].append(h.lineno)
                    if h.type is not None:
                        note_calls(hb, h.type)
                    out_h = flow(h.body, hb, loops, handlers)
                    if out_h is not None:
                        outs.append(out_h)
                if st.finalbody:
                    fin = new_block()
                    for o in outs:
                        link(o, fin)
                    cur = flow(st.finalbody, fin, loops, handlers)
                else:
                    if outs:
                        join = new_block()
                        for o in outs:
                            link(o, join)
                        cur = join
                    else:
                        cur = None
            else:
                note(cur, st)
        return cur

    entry = new_block()
    out = flow(list(getattr(fn_node, "body", [])), entry, [], [])
    if out is not None:
        to_exit(out)
    for b in blocks:
        del b["_raises"]
        b["calls"] = sorted(set(b["calls"]))
    return {"blocks": blocks}


class _FunctionCollector:
    """One bounded walk over a single function body (nested defs get
    their own collector; we do not descend into them here)."""

    def __init__(self, node: ast.AST, qualname: str, cls: Optional[str],
                 parent: Optional[str], strategies: dict,
                 module_state: Optional[set] = None):
        self.node = node
        self.qualname = qualname
        self.cls = cls
        self.parent = parent
        self.local_strategies = strategies  # name -> hedge pin (or None)
        self.module_state = module_state or set()
        self.params: list[str] = []
        self.calls: list[dict] = []
        self.blocking: list[dict] = []
        self.acquires: list[dict] = []
        self.releases: list[dict] = []
        self.awaits_under_lock: list[dict] = []
        self.is_generator = False
        self.returns_exprs: list[ast.AST] = []
        self.escaped: set[str] = set()   # names that leave the function
        self.taint: dict[str, set] = {}
        self.sse_locals: set[str] = set()
        self._lock_stack: list[str] = []
        self._with_items: set[int] = set()  # id() of calls in with-items
        self._try_ctx: list[str] = []       # "except"/"finally" frames
        self._iter_calls: set[int] = set()  # id() of for/async-for iters
        self.blocking_api = False           # @blocking_api-decorated
        # concurrency facts (own ordered walk, _collect_concurrency):
        # accesses = source-order events over shared lvalues in THIS
        # frame ("r" read / "w" write / "a" await / "c" call);
        # lock_acqs = every lock acquisition with the locks already held
        self.accesses: list[dict] = []
        self.lock_acqs: list[dict] = []
        self._cw_locks: list[str] = []
        self._cw_terminal = 0  # inside a return/raise expression
        # allocation-site points-to (ISSUE 20): local name -> "Cls@line"
        # for `x = Cls(...)` bindings (aliases copy the site), so lock
        # identity can distinguish two instances of one class
        self._cw_alloc: dict[str, str] = {}
        # receiver typing facts (ISSUE 20): local/param name ->
        # {"k": "ann"|"call"|"isinstance", "t": "dotted.chain"} — pass 2
        # ranks these above unique-method CHA when resolving bare
        # attribute calls
        self.var_types: dict[str, dict] = {}

        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                self.params.append(arg.arg)
        for p in self.params:
            self.taint[p] = {p}
            if _SSE_NAME.search(p):
                self.sse_locals.add(p)

    # -- taint helpers ----------------------------------------------------

    def _expr_taint(self, expr: Optional[ast.AST]) -> set:
        if expr is None:
            return set()
        out: set = set()
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name):
                out |= self.taint.get(sub.id, set())
            elif isinstance(sub, ast.Call):
                cn = chain_segments(sub.func)
                if cn and _DECRYPT.search(cn[-1]):
                    out.add("<decrypt>")
        return out

    def _bind(self, target: ast.AST, labels: set, from_sse_expr: bool):
        if isinstance(target, ast.Name):
            self.taint[target.id] = self.taint.get(target.id, set()) | labels
            if _SSE_NAME.search(target.id) or from_sse_expr:
                self.sse_locals.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, labels, from_sse_expr)

    # -- the walk ---------------------------------------------------------

    def run(self) -> None:
        # two monotone passes: the second pass sees bindings made later
        # in the first (good enough for helper-chain shapes; loops in
        # the taint lattice only ever add labels)
        body = list(ast.iter_child_nodes(self.node))
        for _ in range(2):
            self.calls.clear()
            self.blocking.clear()
            self.acquires.clear()
            self.releases.clear()
            self.awaits_under_lock.clear()
            self._lock_stack.clear()
            self._with_items.clear()
            self._try_ctx.clear()
            self._iter_calls.clear()
            for child in body:
                self._visit(child, awaited=False)
        self._mark_return_calls()
        self._collect_var_types()
        self._collect_concurrency()

    def _visit(self, node: ast.AST, awaited: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested scopes summarized separately
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            self.is_generator = True
        if isinstance(node, ast.Return) and node.value is not None:
            self.returns_exprs.append(node.value)
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    self.escaped.add(sub.id)
        if isinstance(node, ast.Try):
            # exit-path contexts: releases/calls inside an except
            # handler or a finally: block carry a "ctx" marker so the
            # (now cross-function) GL11 logic can classify them from
            # the summary alone
            for st in node.body:
                self._visit(st, awaited=False)
            for h in node.handlers:
                if h.type is not None:
                    self._visit(h.type, awaited=False)
                self._try_ctx.append("except")
                for st in h.body:
                    self._visit(st, awaited=False)
                self._try_ctx.pop()
            for st in node.orelse:
                self._visit(st, awaited=False)
            self._try_ctx.append("finally")
            for st in node.finalbody:
                self._visit(st, awaited=False)
            self._try_ctx.pop()
            return
        if isinstance(node, ast.Assign):
            labels = self._expr_taint(node.value)
            sse_expr = any(lb in self.sse_locals or lb == "<decrypt>"
                           for lb in labels)
            for t in node.targets:
                self._bind(t, labels, sse_expr)
                if isinstance(t, ast.Attribute):
                    # stored on an object: ownership escapes
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name):
                            self.escaped.add(sub.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                and node.value is not None:
            self._bind(node.target, self._expr_taint(node.value), False)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._bind(node.target, self._expr_taint(node.iter), False)
            if isinstance(node.iter, ast.Call):
                # `for x in gen(...)` — iterating a generator RUNS its
                # body on this frame (GL10's generator blindness)
                self._iter_calls.add(id(node.iter))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            lockish = None
            for item in node.items:
                segs = chain_segments(item.context_expr)
                if any("lock" in s.lower() for s in segs):
                    lockish = ".".join(segs)
                if isinstance(item.context_expr, ast.Call):
                    self._with_items.add(id(item.context_expr))
                if item.optional_vars is not None:
                    self._bind(item.optional_vars,
                               self._expr_taint(item.context_expr), False)
            if lockish is not None:
                self._lock_stack.append(lockish)
                for item in node.items:
                    self._visit(item.context_expr, awaited=False)
                for child in node.body:
                    self._visit(child, awaited=False)
                self._lock_stack.pop()
                return
        elif isinstance(node, ast.Await):
            if self._lock_stack:
                self.awaits_under_lock.append({
                    "line": node.lineno,
                    "lock": self._lock_stack[-1],
                })
            if isinstance(node.value, ast.Call):
                self._visit_call(node.value, awaited=True)
                for arg in ast.iter_child_nodes(node.value):
                    self._visit(arg, awaited=False)
                return
        elif isinstance(node, ast.Call):
            self._visit_call(node, awaited=awaited)

        for child in ast.iter_child_nodes(node):
            self._visit(child, awaited=False)

    # -- call records -----------------------------------------------------

    def _visit_call(self, node: ast.Call, awaited: bool) -> None:
        ref = _call_ref(node.func)
        segs = chain_segments(node.func)
        name = segs[-1] if segs else ""
        recv = segs[:-1]

        # every Name argument escapes (ownership may transfer)
        for a in list(node.args) + [k.value for k in node.keywords
                                    if k.value is not None]:
            if isinstance(a, ast.Name):
                self.escaped.add(a.id)

        # thread-hop / partial unwrapping: the FIRST callable argument
        # becomes its own edge
        if name in THREAD_HOPS or name == "partial":
            fn_args = node.args
            if name == "run_in_executor" and len(fn_args) >= 2:
                fn_args = fn_args[1:]
            if fn_args:
                inner = _call_ref(fn_args[0])
                if inner is not None:
                    self.calls.append({
                        "ref": inner, "line": node.lineno,
                        "end_line": getattr(node, "end_lineno", node.lineno),
                        "via_thread": name in THREAD_HOPS,
                        "awaited": False, "name": inner[-1],
                        "recv": [], "kwargs": [], "args": [], "kw": {},
                        "ops": [],
                        "ctx": self._try_ctx[-1] if self._try_ctx
                               else "",
                    })

        if ref is None:
            return

        rec = {
            "ref": ref,
            "line": node.lineno,
            "end_line": getattr(node, "end_lineno", node.lineno),
            "via_thread": False,
            "awaited": awaited,
            "name": name,
            "recv": recv,
            "kwargs": sorted(k.arg for k in node.keywords
                             if k.arg is not None),
            "args": [self._arg_desc(a) for a in node.args],
            "kw": {k.arg: self._arg_desc(k.value)
                   for k in node.keywords
                   if k.arg is not None
                   and self._arg_desc(k.value) is not None},
            "ops": _payload_ops(node),
            "ctx": self._try_ctx[-1] if self._try_ctx else "",
        }
        rec["kw"] = {k: v for k, v in rec["kw"].items() if v}
        if id(node) in self._iter_calls:
            rec["iterated"] = True
        self.calls.append(rec)

        # blocking atoms (non-awaited only: an awaited call is a
        # coroutine by definition)
        if not awaited:
            dn = dotted_name(node.func)
            if dn in IO_BLOCKING_CALLS:
                self.blocking.append(
                    {"target": dn, "line": node.lineno, "kind": "io"})
            elif name in DB_METHODS and recv \
                    and _DB_RECEIVER.search(recv[-1]):
                # "ref" lets pass 2 override the receiver-name
                # heuristic with the @blocking_api annotation when the
                # call resolves to an in-project function
                self.blocking.append(
                    {"target": ".".join(segs), "line": node.lineno,
                     "kind": "db", "ref": ref})

        # resource discipline facts
        if name in ACQUIRE_METHODS and recv:
            self.acquires.append({
                "line": node.lineno, "recv": recv[-1],
                "method": name, "awaited": awaited,
                "in_with": id(node) in self._with_items,
                "names": sorted(self._acq_names(
                    {"line": node.lineno, "method": name})),
                "ctx": self._try_ctx[-1] if self._try_ctx else "",
            })
        elif name in RELEASE_METHODS and recv:
            self.releases.append({
                "line": node.lineno, "recv": recv[-1], "method": name,
                "ctx": self._try_ctx[-1] if self._try_ctx else ""})

    def _arg_desc(self, expr: ast.AST) -> Optional[dict]:
        out: dict = {}
        tset = self._expr_taint(expr)
        labels = set(tset) & (set(self.params) | {"<decrypt>"})
        names_in = {sub.id for sub in ast.walk(expr)
                    if isinstance(sub, ast.Name)}
        # "<sse>" marks an argument built from SSE-C state in THIS
        # scope (sse-named param/local or a decrypt result) — the
        # interprocedural rule taints the callee's parameter outright
        if names_in & self.sse_locals or "<decrypt>" in tset:
            labels.add("<sse>")
        if labels:
            out["t"] = sorted(labels)
        if isinstance(expr, ast.Call):
            cn = chain_segments(expr.func)
            if cn and cn[-1] == "RequestStrategy":
                hedge = None
                for k in expr.keywords:
                    if k.arg == "hedge" and isinstance(k.value,
                                                      ast.Constant):
                        hedge = bool(k.value.value)
                out["s"] = {"k": "inline", "hedge": hedge}
        elif isinstance(expr, ast.Name):
            # the bare name itself (GL11v2 matches it against callee
            # release facts to see a resource released one frame down)
            out["n"] = expr.id
            if expr.id in self.local_strategies:
                out["s"] = {"k": "local",
                            "hedge": self.local_strategies[expr.id]}
            elif expr.id in self.params:
                out["s"] = {"k": "param", "name": expr.id}
        return out or None

    def _mark_return_calls(self) -> None:
        """Post-pass annotations that need whole-body context: which
        call records sit inside a `return` expression ("in_ret") and
        which names each call's result was bound to ("bound")."""
        ret_calls: set[tuple] = set()
        for r in self.returns_exprs:
            for sub in ast.walk(r):
                if isinstance(sub, ast.Call):
                    cs = chain_segments(sub.func)
                    if cs:
                        ret_calls.add((sub.lineno, cs[-1]))
        bound: dict[tuple, list] = {}
        for sub in ast.walk(self.node):
            if not isinstance(sub, ast.Assign):
                continue
            v = sub.value
            if isinstance(v, ast.Await):
                v = v.value
            if not isinstance(v, ast.Call):
                continue
            cs = chain_segments(v.func)
            if not cs:
                continue
            names = sorted(t.id for t in sub.targets
                           if isinstance(t, ast.Name))
            if names:
                bound[(v.lineno, cs[-1])] = names
        for rec in self.calls:
            key = (rec["line"], rec["name"])
            if key in ret_calls:
                rec["in_ret"] = True
            if key in bound:
                rec["bound"] = bound[key]

    # -- receiver typing facts (ISSUE 20) --------------------------------

    def _ann_chain(self, ann: Optional[ast.AST]) -> Optional[str]:
        """Dotted chain of a simple annotation: Name / Attribute, a
        string literal forward reference, or Optional[X] unwrapped one
        level. Anything fancier returns None (no fact beats a wrong
        fact)."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            txt = ann.value.strip()
            return txt if txt.replace(".", "").isidentifier() else None
        if isinstance(ann, ast.Subscript):
            segs = chain_segments(ann.value)
            if segs and segs[-1] == "Optional":
                return self._ann_chain(ann.slice)
            return None
        if isinstance(ann, (ast.Name, ast.Attribute)):
            segs = chain_segments(ann)
            return ".".join(segs) if segs else None
        return None

    def _collect_var_types(self) -> None:
        """Local receiver types, best-evidence-last: parameter
        annotations seed the map, `x = Cls(...)` / `x = y` assignments
        overwrite (direct evidence), `isinstance(x, Cls)` guards fill
        gaps only. Pass 2 consults these before unique-method CHA."""
        if isinstance(self.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = self.node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                t = self._ann_chain(arg.annotation)
                if t and arg.arg not in ("self", "cls"):
                    self.var_types[arg.arg] = {"k": "ann", "t": t}
        stack = list(ast.iter_child_nodes(self.node))[::-1]
        guards: list[tuple[str, str]] = []
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                tgt = n.targets[0].id
                if isinstance(n.value, ast.Call):
                    segs = chain_segments(n.value.func)
                    if segs:
                        self.var_types[tgt] = {"k": "call",
                                               "t": ".".join(segs)}
                elif isinstance(n.value, ast.Name):
                    src = self.var_types.get(n.value.id)
                    if src is not None:
                        self.var_types[tgt] = dict(src)
                    else:
                        self.var_types.pop(tgt, None)
                else:
                    # rebound to something we can't type: forget
                    self.var_types.pop(tgt, None)
            elif isinstance(n, ast.Call):
                segs = chain_segments(n.func)
                if segs and segs[-1] == "isinstance" \
                        and len(n.args) == 2 \
                        and isinstance(n.args[0], ast.Name) \
                        and isinstance(n.args[1], (ast.Name,
                                                   ast.Attribute)):
                    t = ".".join(chain_segments(n.args[1]))
                    if t:
                        guards.append((n.args[0].id, t))
            stack.extend(list(ast.iter_child_nodes(n))[::-1])
        for var, t in guards:
            self.var_types.setdefault(var, {"k": "isinstance", "t": t})

    # -- concurrency facts (GL12 / GL13) ---------------------------------

    def _lvalue_of(self, expr: ast.AST) -> Optional[list]:
        """Shared-state lvalue behind an expression: `self.X` (and any
        subscript of it) -> ["self", X]; a module-state name ->
        ["mod", name]. Local names and params are not shared state."""
        e = expr
        while isinstance(e, ast.Subscript):
            e = e.value
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
                and e.value.id in ("self", "cls"):
            return ["self", e.attr]
        if isinstance(e, ast.Name) and e.id in self.module_state:
            return ["mod", e.id]
        return None

    def _collect_concurrency(self) -> None:
        """One extra source-order walk collecting the facts the GL12
        (await-interleaving) and GL13 (lock-order) rules consume:

          * `accesses`: ordered events over shared lvalues — "r" read,
            "w" write (assignment, augmented assignment, del, or a
            mutating container method), "a" await (with the locks held
            and the awaited call's ref), "c" project call (so a write
            performed by a self-call lands at the call line);
          * `lock_acqs`: every lock acquisition (`with`/`async with` on
            a lock-named expression, or a bare `.acquire()` on one)
            with the locks already held at that point.

        The walk linearizes control flow by source order — good enough
        for lint — with two refinements: a `while` loop's test is
        re-emitted after its body, so the guard-loop idiom (await
        inside the loop, condition re-checked before falling through)
        does not read as a stale check; and a loop body containing an
        await is emitted TWICE (the CFG back-edge unrolled once, ISSUE
        20), so a loop-carried race — read late in iteration i, write
        after the await early in iteration i+1 — produces the r/a/w
        sequence GL12 fires on. Duplicate lock_acqs/call events from
        the unroll are harmless: GL12 and GL13 both dedup downstream."""
        for child in ast.iter_child_nodes(self.node):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                self._cw_visit(child)

    def _cw_emit(self, kind: str, line: int, lv=None, **extra) -> None:
        ev = {"k": kind, "line": line}
        if lv is not None:
            ev["lv"] = lv
        ev.update(extra)
        self.accesses.append(ev)

    def _cw_lock_token(self, segs: list) -> str:
        """Lock identity token (ISSUE 20): the attribute path, with a
        local receiver rewritten to its allocation site when known
        (`g = Guard(...); g.lock` -> "<Guard@12>.lock"), so two
        instances of one class stay distinct while two aliases of one
        instance collapse to the same identity."""
        segs = [s for s in segs if s != "acquire"]
        if segs and segs[0] not in ("self", "cls"):
            site = self._cw_alloc.get(segs[0])
            if site is not None:
                return ".".join([f"<{site}>"] + segs[1:])
        return ".".join(segs)

    def _cw_visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # EVERY lock-ish item counts, in order: `with a, b:` is an
            # a -> b acquisition edge (items after the first are taken
            # while the earlier ones are held)
            pushed = 0
            for item in node.items:
                self._cw_visit(item.context_expr)
                segs = chain_segments(item.context_expr)
                if any(LOCK_SEG in s.lower() for s in segs):
                    lock = self._cw_lock_token(segs)
                    self.lock_acqs.append({
                        "lock": lock, "line": node.lineno,
                        "held": list(self._cw_locks),
                        "sync": isinstance(node, ast.With)})
                    self._cw_locks.append(lock)
                    pushed += 1
            for st in node.body:
                self._cw_visit(st)
            for _ in range(pushed):
                self._cw_locks.pop()
            return
        if isinstance(node, ast.While):
            self._cw_visit(node.test)
            # back-edge unroll (ISSUE 20): a body that awaits is
            # emitted twice so a read late in iteration i meets the
            # write after the await in iteration i+1
            rounds = 2 if any(_contains_await(st)
                              for st in node.body) else 1
            for _ in range(rounds):
                for st in node.body:
                    self._cw_visit(st)
                self._cw_visit(node.test)  # re-evaluated before exit
            for st in node.orelse:
                self._cw_visit(st)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._cw_visit(node.iter)
            rounds = 2 if any(_contains_await(st)
                              for st in node.body) else 1
            for _ in range(rounds):
                for st in node.body:
                    self._cw_visit(st)
            for st in node.orelse:
                self._cw_visit(st)
            return
        if isinstance(node, ast.Assign):
            self._cw_visit(node.value)
            # allocation-site tracking for lock identity (ISSUE 20)
            if len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                if isinstance(node.value, ast.Call):
                    vsegs = chain_segments(node.value.func)
                    if vsegs and vsegs[-1][:1].isupper():
                        self._cw_alloc[tgt] = \
                            f"{vsegs[-1]}@{node.value.lineno}"
                    else:
                        self._cw_alloc.pop(tgt, None)
                elif isinstance(node.value, ast.Name):
                    site = self._cw_alloc.get(node.value.id)
                    if site is not None:
                        self._cw_alloc[tgt] = site
                    else:
                        self._cw_alloc.pop(tgt, None)
                else:
                    self._cw_alloc.pop(tgt, None)
            # a bare True/False store is idempotent-convergent (every
            # racing task writes the same terminal flag value) — GL12
            # records but does not fire on it
            const_flag = isinstance(node.value, ast.Constant) \
                and node.value.value in (True, False)
            for t in node.targets:
                lv = self._lvalue_of(t)
                if lv is not None:
                    if const_flag and not isinstance(t, ast.Subscript):
                        self._cw_emit("w", t.lineno, lv, flag=True)
                    else:
                        self._cw_emit("w", t.lineno, lv)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for el in t.elts:
                        elv = self._lvalue_of(el)
                        if elv is not None:
                            self._cw_emit("w", el.lineno, elv)
                if isinstance(t, ast.Subscript):
                    self._cw_visit(t.slice)
            return
        if isinstance(node, ast.AugAssign):
            # read-modify-write: the read precedes the value (CPython
            # loads the target before evaluating the RHS), so an await
            # INSIDE the value still races; but as a callee's write it
            # is accretive (it re-reads at mutation time)
            lv = self._lvalue_of(node.target)
            if lv is not None:
                self._cw_emit("r", node.lineno, lv)
            self._cw_visit(node.value)
            if lv is not None:
                self._cw_emit("w", node.lineno, lv, acc=True)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                lv = self._lvalue_of(t)
                if lv is not None:
                    self._cw_emit("w", t.lineno, lv)
                if isinstance(t, ast.Subscript):
                    self._cw_visit(t.slice)
            return
        if isinstance(node, (ast.Return, ast.Raise)):
            # control leaves this frame: an await here cannot precede
            # a later write in THIS frame, and the awaited callee's
            # writes land after the caller is done deciding
            self._cw_terminal += 1
            for child in ast.iter_child_nodes(node):
                self._cw_visit(child)
            self._cw_terminal -= 1
            if isinstance(node, ast.Return):
                # barrier: any flow that crossed an earlier await ends
                # here, so a textually-later write belongs to a branch
                # that never awaited (`if batch: await ...; return` /
                # `else: write`) — raise is NOT a barrier (exceptional
                # guards between await and write still race)
                self._cw_emit("x", node.lineno, None)
            return
        if isinstance(node, ast.Await):
            ref = None
            if isinstance(node.value, ast.Call):
                # the "a" event carries the ref itself — no separate
                # "c" event, which would wrongly count the awaited
                # callee's writes as landing BEFORE the preemption
                ref = _call_ref(node.value.func)
                self._cw_call(node.value, emit_call=False)
            else:
                self._cw_visit(node.value)
            self._cw_emit("a", node.lineno, None,
                          locks=list(self._cw_locks), call=ref,
                          **({"ret": True} if self._cw_terminal else {}))
            return
        if isinstance(node, ast.Call):
            self._cw_call(node)
            return
        if isinstance(node, ast.Attribute):
            lv = self._lvalue_of(node)
            if lv is not None:
                self._cw_emit("r", node.lineno, lv)
                return
        if isinstance(node, ast.Name):
            lv = self._lvalue_of(node)
            if lv is not None:
                self._cw_emit("r", node.lineno, lv)
            return
        if isinstance(node, ast.Subscript):
            lv = self._lvalue_of(node)
            if lv is not None:
                self._cw_emit("r", node.lineno, lv)
                self._cw_visit(node.slice)
                return
        for child in ast.iter_child_nodes(node):
            self._cw_visit(child)

    def _cw_call(self, node: ast.Call, emit_call: bool = True) -> None:
        segs = chain_segments(node.func)
        name = segs[-1] if segs else ""
        recv_lv = None
        if isinstance(node.func, ast.Attribute):
            recv_lv = self._lvalue_of(node.func.value)
            # receiver chain below the method name still carries reads
            # (`self.peers[p].ring.push(x)` reads self.peers) — but a
            # mutating method ON a tracked lvalue is one atomic write,
            # not a stale read followed by a write
            if recv_lv is None or name not in MUT_METHODS:
                self._cw_visit(node.func.value)
        for a in node.args:
            self._cw_visit(a)
        for k in node.keywords:
            self._cw_visit(k.value)
        db_recv = bool(segs[:-1]) and bool(
            _DB_RECEIVER.search(segs[-2]))
        if recv_lv is not None and name in MUT_METHODS and not db_recv:
            if name in ACCRETIVE_METHODS:
                # re-validating read at mutation time (see above)
                self._cw_emit("r", node.lineno, recv_lv)
                self._cw_emit("w", node.lineno, recv_lv, acc=True)
            else:
                self._cw_emit("w", node.lineno, recv_lv)
        if name == "acquire" and segs[:-1] \
                and any(LOCK_SEG in s.lower() for s in segs[:-1]):
            self.lock_acqs.append({
                "lock": self._cw_lock_token(segs[:-1]),
                "line": node.lineno,
                "held": list(self._cw_locks), "sync": False})
        if not emit_call:
            return
        ref = _call_ref(node.func)
        if ref is not None and name not in MUT_METHODS \
                and (ref[0] in ("self", "name") or self._cw_locks):
            self._cw_emit("c", node.lineno, None, call=ref,
                          held=list(self._cw_locks))

    # -- GL11 support facts ----------------------------------------------
    # (the leak DECISION moved to pass 2 in ISSUE 14 so acquire/release
    # facts can settle across call-graph edges; the collector only
    # records the structural facts the rule consumes)

    def _acq_names(self, acq: dict) -> set:
        """Names the acquired value was bound to (release via the
        value: `lease = broker.acquire(); ...; lease.release()`)."""
        names = set()
        for sub in ast.walk(self.node):
            if not isinstance(sub, ast.Assign):
                continue
            for c in ast.walk(sub.value):
                if isinstance(c, ast.Call) and c.lineno == acq["line"]:
                    cs = chain_segments(c.func)
                    if cs and cs[-1] == acq["method"]:
                        for t in sub.targets:
                            if isinstance(t, ast.Name):
                                names.add(t.id)
        return names

    # -- output -----------------------------------------------------------

    def summary(self, path: str, module: str, nested: dict) -> dict:
        is_async = isinstance(self.node, ast.AsyncFunctionDef)
        name = getattr(self.node, "name", "<lambda>")
        param_return = sorted(
            set().union(*[self._expr_taint(r) for r in self.returns_exprs])
            & set(self.params)) if self.returns_exprs else []
        ret_names = sorted({sub.id for r in self.returns_exprs
                            for sub in ast.walk(r)
                            if isinstance(sub, ast.Name)})
        return {
            "name": name,
            "qualname": self.qualname,
            "class": self.cls or "",
            "parent": self.parent or "",
            "module": module,
            "path": path,
            "line": getattr(self.node, "lineno", 1),
            "is_async": is_async,
            "is_generator": self.is_generator,
            "is_method": bool(self.cls) and bool(self.params)
                         and self.params[0] in ("self", "cls"),
            "params": list(self.params),
            "mutation_name": bool(MUTATION_NAME_RE.search(name)),
            "sse_sources": sorted(self.sse_locals),
            "param_return": param_return,
            "ret_names": ret_names,
            "escaped": sorted(self.escaped),
            "blocking": sorted(self.blocking,
                               key=lambda b: (b["line"], b["target"])),
            "blocking_api": self.blocking_api,
            "calls": self.calls,
            "acquires": self.acquires,
            "releases": self.releases,
            "awaits_under_lock": self.awaits_under_lock,
            "accesses": self.accesses,
            "lock_acqs": self.lock_acqs,
            "alloc_sites": {k: self._cw_alloc[k]
                            for k in sorted(self._cw_alloc)},
            "var_types": {k: self.var_types[k]
                          for k in sorted(self.var_types)},
            "cfg": build_cfg(self.node),
            "nested": {k: nested[k] for k in sorted(nested)},
        }


def _local_strategy_pins(fn: ast.AST) -> dict:
    """name -> hedge pin (True/False/None) for `x = RequestStrategy(...)`
    bindings in this function body."""
    out: dict = {}
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            segs = chain_segments(sub.value.func)
            if segs and segs[-1] == "RequestStrategy":
                hedge = None
                for k in sub.value.keywords:
                    if k.arg == "hedge" and isinstance(k.value,
                                                      ast.Constant):
                        hedge = bool(k.value.value)
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = hedge
    return out


_MUTABLE_INITS = (ast.Dict, ast.List, ast.Set, ast.Call,
                  ast.DictComp, ast.ListComp, ast.SetComp)


def _top_level_state(tree: ast.Module) -> set:
    """Module-level names bound to mutable-looking values (dict/list/
    set/call/comprehension) — the shared-state census GL09 pioneered,
    reused here so GL12 can track module-global lvalues. Restricted to
    true module scope (no descending into defs/classes)."""
    out: set = set()

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Assign) \
                    and isinstance(child.value, _MUTABLE_INITS):
                for t in child.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif isinstance(child, ast.AnnAssign) \
                    and child.value is not None \
                    and isinstance(child.value, _MUTABLE_INITS) \
                    and isinstance(child.target, ast.Name):
                out.add(child.target.id)
            else:
                walk(child)

    walk(tree)
    return out


def _has_blocking_api_marker(node) -> bool:
    """`@blocking_api` decorator on a def, or a truthy `blocking_api =
    True` class attribute (checked by the caller for ClassDef)."""
    for dec in getattr(node, "decorator_list", []):
        segs = chain_segments(dec)
        if segs and segs[-1] == "blocking_api":
            return True
    return False


def _class_blocking_api(node: ast.ClassDef) -> bool:
    for child in node.body:
        if isinstance(child, ast.Assign):
            for t in child.targets:
                if isinstance(t, ast.Name) and t.id == "blocking_api" \
                        and isinstance(child.value, ast.Constant) \
                        and bool(child.value.value):
                    return True
    return False


def summarize_tree(tree: ast.Module, rel_path: str) -> dict:
    """The whole pass-1 product for one file: module facts (imports,
    classes) + per-function summaries. Pure function of the AST."""
    module = module_name_of(rel_path)
    module_state = _top_level_state(tree)
    # a package __init__ IS its package: `from .core import x` there
    # resolves against the package itself, one level shallower than the
    # same import in a sibling module
    is_package = rel_path.replace("\\", "/").endswith("/__init__.py")
    imports: dict[str, str] = {}
    classes: dict[str, dict] = {}
    functions: dict[str, dict] = {}

    def handle_import(node):
        if isinstance(node, ast.Import):
            for alias in node.names:
                imports[alias.asname or alias.name.split(".")[0]] = \
                    alias.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = module.split(".")
                # `from . import x` in pkg/mod.py: level 1 = pkg;
                # in pkg/__init__.py: level 1 = pkg too (module_name_of
                # already collapsed the __init__ component)
                drop = node.level - 1 if is_package else node.level
                if drop:
                    parts = parts[: len(parts) - drop]
                base = ".".join(parts + ([node.module]
                                         if node.module else []))
            for alias in node.names:
                if alias.name == "*":
                    continue
                imports[alias.asname or alias.name] = \
                    f"{base}.{alias.name}" if base else alias.name

    def walk_scope(node, class_stack: list[str],
                   parent_fn: Optional[str]) -> dict:
        """Returns {bare_name: qualname} of functions defined directly
        in this scope (the caller's name-resolution context)."""
        own: dict[str, str] = {}
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.Import, ast.ImportFrom)):
                handle_import(child)
            elif isinstance(child, ast.ClassDef):
                cname = ".".join(class_stack + [child.name])
                classes[cname] = {
                    "bases": sorted(
                        s for b in child.bases
                        for s in [".".join(chain_segments(b))] if s),
                    "methods": {},
                    "line": child.lineno,
                    "blocking_api": _class_blocking_api(child),
                }
                methods = walk_scope(child, class_stack + [child.name],
                                     None)
                classes[cname]["methods"] = {
                    k.rsplit(".", 1)[-1]: v for k, v in methods.items()}
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qn = (f"{parent_fn}.{child.name}" if parent_fn
                      else ".".join(class_stack + [child.name]))
                coll = _FunctionCollector(
                    child, qn,
                    cls=".".join(class_stack) if class_stack else None,
                    parent=parent_fn,
                    strategies=_local_strategy_pins(child),
                    module_state=module_state)
                coll.blocking_api = _has_blocking_api_marker(child)
                coll.run()
                nested = walk_scope(child, [], qn)
                functions[qn] = coll.summary(rel_path, module, {
                    k.rsplit(".", 1)[-1]: v for k, v in nested.items()})
                own[child.name] = qn
            else:
                # module-level statements may nest defs inside
                # try/if blocks; recurse without changing scope kind
                if not isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef, ast.Lambda)):
                    own.update(walk_scope(child, class_stack, parent_fn))
        return own

    top = walk_scope(tree, [], None)
    return {
        "module": module,
        "path": rel_path,
        "imports": {k: imports[k] for k in sorted(imports)},
        "classes": {k: classes[k] for k in sorted(classes)},
        "top_functions": {k: top[k] for k in sorted(top)},
        "functions": {k: functions[k] for k in sorted(functions)},
    }


def summary_fingerprint(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8", "replace")).hexdigest()


def summary_json(file_summary: dict) -> str:
    """Canonical byte form (the determinism + cache contract)."""
    return json.dumps(file_summary, sort_keys=True, separators=(",", ":"))


class DataflowState:
    """Pass-1 product for a whole project: file summaries (cache-aware)
    plus the resolved call graph. Built once per analyze run, shared by
    every `needs_dataflow` rule via project.data["_dataflow"]."""

    def __init__(self, file_contexts, summary_cache: Optional[dict] = None):
        from .callgraph import CallGraph

        cache = summary_cache or {}
        self.summaries: dict[str, dict] = {}
        self.fingerprints: dict[str, str] = {}
        self.cache_hits = 0
        for ctx in file_contexts:
            fp = summary_fingerprint(ctx.source)
            self.fingerprints[ctx.rel_path] = fp
            ent = cache.get(ctx.rel_path)
            # the engine version gates reuse too: CI's restore-keys
            # fallback serves a PREVIOUS tree's cache after any
            # analyzer change, and per-file hashes alone would then
            # happily feed old-schema summaries to new rules
            if ent is not None and ent.get("sha256") == fp \
                    and ent.get("v") == SUMMARY_VERSION:
                self.summaries[ctx.rel_path] = ent["summary"]
                self.cache_hits += 1
            else:
                self.summaries[ctx.rel_path] = summarize_tree(
                    ctx.tree, ctx.rel_path)
        self.graph = CallGraph(self.summaries)

    def cache_payload(self) -> dict:
        """What --summary-cache persists: per-file hash + engine
        version + summary."""
        return {rel: {"sha256": self.fingerprints[rel],
                      "v": SUMMARY_VERSION, "summary": s}
                for rel, s in sorted(self.summaries.items())}
