"""garage-lint core: violations, waivers, rule base class.

Project-invariant static analysis (ISSUE 5). Every rule here encodes an
invariant that an earlier PR established by hand and that nothing else
enforces: blocking work leaves the event loop (PR 2), non-idempotent
RPCs never hedge (PR 4), SSE-C plaintext never enters the read cache
(PR 3), background tasks are retained and cancelled orphan-free,
exceptions are not silently swallowed (Yuan et al., OSDI '14 —
"Simple Testing Can Prevent Most Critical Failures": the majority of
catastrophic distributed-storage failures traced to exactly the
error-handling stubs GL05 flags).

Stdlib-only by design (`ast` + `re`): the repo's optional-dependency
discipline applies to its own tooling.

Waiver syntax, checked by the framework itself::

    risky_call()  # lint: ignore[GL05] reason the invariant is upheld

A waiver must carry a reason, must name a rule that actually fires on
that statement, and a waiver that no longer suppresses anything is
itself an error (GL00) — suppressions cannot rot silently.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Optional

# GL00 is the framework's own hygiene rule: stale or malformed waivers,
# stale baseline entries, unparseable files. It cannot be waived.
META_RULE = "GL00"

WAIVER_RE = re.compile(
    r"#\s*lint:\s*ignore\[([A-Za-z0-9,\s]+)\]\s*(.*)$")


@dataclass
class Violation:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str
    context: str = "<module>"   # enclosing def/class qualname
    waived: bool = False
    baselined: bool = False

    @property
    def active(self) -> bool:
        return not (self.waived or self.baselined)

    def key(self) -> tuple:
        return (self.rule, self.path, self.line, self.col, self.message)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
            "context": self.context, "waived": self.waived,
            "baselined": self.baselined,
        }

    def render(self) -> str:
        flag = " (waived)" if self.waived else \
            " (baselined)" if self.baselined else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}{flag}")


@dataclass
class Waiver:
    line: int
    rules: tuple[str, ...]
    reason: str
    used: bool = False
    # which of `rules` actually suppressed something — staleness is
    # per-rule since ISSUE 20, so a combined GL05+GL07 waiver with
    # only GL05 firing reports GL07 stale instead of staying silent
    used_rules: set = field(default_factory=set)


def extract_waivers(source: str) -> list[Waiver]:
    """Waivers live in real COMMENT tokens only — a waiver example
    inside a docstring is prose, not a suppression (tokenize, not a
    line regex, so strings can't fool it)."""
    out = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = WAIVER_RE.search(tok.string)
            if m:
                rules = tuple(r.strip().upper()
                              for r in m.group(1).split(",") if r.strip())
                out.append(Waiver(line=tok.start[0], rules=rules,
                                  reason=m.group(2).strip()))
    except (tokenize.TokenizeError, SyntaxError, IndentationError):
        pass  # unparseable files already surface as GL00
    return out


class FileContext:
    """Per-file state shared by all rules during the single AST pass.

    The walker maintains the scope stacks; rules read them and call
    report(). Waiver application happens after the pass, in
    apply_waivers(), so a rule never needs waiver logic of its own.
    """

    def __init__(self, path: str, rel_path: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.waivers = extract_waivers(source)
        self.violations: list[Violation] = []
        # scope stacks, maintained by the walker
        # frames: (node, name, is_async, meta-dict)
        self.func_stack: list[tuple[ast.AST, str, bool, dict]] = []
        self.class_stack: list[str] = []
        # with / async-with frames whose context expression names a
        # lock (GL06 learned sync `with lock():` in ISSUE 9 — a
        # threading lock held across an await serializes waiters just
        # the same)
        self.lock_stack: list[ast.AST] = []

    # ---- scope queries --------------------------------------------------

    @property
    def is_test(self) -> bool:
        parts = self.rel_path.split("/")
        name = parts[-1]
        return ("tests" in parts or name.startswith("test_")
                or name == "conftest.py")

    @property
    def is_harness(self) -> bool:
        """Test-infrastructure files that opt into a scoped rule
        subset (GL04/GL05/GL07): the cluster-in-a-box harness, the
        shared conftest, and the bench driver — harness code orphaning
        tasks or swallowing exceptions silently corrupts chaos-soak
        verdicts (ISSUE 9 satellite)."""
        name = self.rel_path.split("/")[-1]
        return name in ("clusterbox.py", "conftest.py", "bench.py")

    @property
    def in_async_def(self) -> bool:
        """True when the INNERMOST function frame is async — a blocking
        call inside a nested sync def/lambda runs off-loop (that is the
        asyncio.to_thread pattern) and must not fire GL01."""
        if not self.func_stack:
            return False
        return self.func_stack[-1][2]

    @property
    def func_meta(self) -> dict:
        """Per-function scratch dict (arg names, local assigns, strategy
        bindings) prepared by the walker on function entry."""
        return self.func_stack[-1][3] if self.func_stack else {}

    def qualname(self) -> str:
        names = list(self.class_stack)
        names += [n for _, n, _, _ in self.func_stack]
        return ".".join(names) if names else "<module>"

    # ---- reporting ------------------------------------------------------

    def report(self, rule_id: str, node: ast.AST, message: str) -> None:
        v = Violation(
            rule=rule_id, path=self.rel_path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message, context=self.qualname(),
        )
        v._end_line = getattr(node, "end_lineno", None)  # type: ignore
        self.violations.append(v)

    # ---- waivers --------------------------------------------------------

    def apply_waivers(self, active_rules: "set[str] | None" = None) -> None:
        """Mark violations covered by an inline waiver, then report
        waiver hygiene: missing reason, stale (suppresses nothing).
        A waiver covers a violation when it sits on any line the
        flagged node's statement spans (first line - 1 .. last line),
        so multi-line calls can carry the comment on any of their
        lines. `active_rules` (a --rules subset) exempts waivers for
        rules that did not run from the staleness check — they could
        not possibly have suppressed anything this run."""
        # idempotent under re-settling (analyze_source with a shared
        # project settles after every added file): drop our own prior
        # hygiene output and recompute from scratch
        self.violations = [v for v in self.violations
                           if not getattr(v, "_waiver_hygiene", False)]
        for w in self.waivers:
            w.used = False
            w.used_rules = set()
        spans: dict[int, list[Violation]] = {}
        for v in self.violations:
            spans.setdefault(v.line, []).append(v)
        for w in self.waivers:
            if META_RULE in w.rules:
                v = Violation(
                    rule=META_RULE, path=self.rel_path, line=w.line,
                    col=0, message="GL00 cannot be waived")
                v._waiver_hygiene = True  # type: ignore[attr-defined]
                self.violations.append(v)
                continue
            if not w.reason:
                v = Violation(
                    rule=META_RULE, path=self.rel_path, line=w.line,
                    col=0,
                    message="waiver has no reason: "
                            "`# lint: ignore[RULE] why it is safe`")
                v._waiver_hygiene = True  # type: ignore[attr-defined]
                self.violations.append(v)
                # a reasonless waiver still suppresses nothing
                continue
            for v in self.violations:
                if v.rule in w.rules and self._covers(w, v):
                    v.waived = True
                    w.used = True
                    w.used_rules.add(v.rule)
        for w in self.waivers:
            if not w.reason or META_RULE in w.rules:
                continue
            # staleness is PER-RULE: a multi-rule waiver with one dead
            # rule names exactly the dead one (the others keep working)
            stale = [r for r in w.rules if r not in w.used_rules]
            if active_rules is not None:
                # a rule that didn't run this invocation could not
                # possibly have suppressed anything — exempt it
                stale = [r for r in stale if r in active_rules]
            if not stale:
                continue
            v = Violation(
                rule=META_RULE, path=self.rel_path, line=w.line, col=0,
                message=f"stale waiver for {','.join(stale)}: "
                        "suppresses nothing on this statement")
            v._waiver_hygiene = True  # type: ignore[attr-defined]
            self.violations.append(v)

    def _covers(self, w: Waiver, v: Violation) -> bool:
        if w.line in (v.line, v.line - 1):
            return True
        # multi-line statement: waiver on any spanned line counts
        end = getattr(v, "_end_line", None)
        return end is not None and v.line <= w.line <= end


class Rule:
    """One invariant. Subclasses declare `id`, `name`, `summary` and
    implement any of the hook methods the walker dispatches:

        on_call(node, ctx)           every ast.Call
        on_await(node, ctx)          every ast.Await
        on_expr_stmt(node, ctx)      every ast.Expr statement
        on_except(node, ctx)         every ast.ExceptHandler
        on_function(node, ctx)       every (Async)FunctionDef, on entry
        finish_file(ctx)             after the file's pass
        finish_project(project)      after ALL files (cross-file rules);
                                     returns extra list[Violation]
    """

    id: str = "GL??"
    name: str = "unnamed"
    summary: str = ""

    def applies_to(self, ctx: FileContext) -> bool:
        return not ctx.is_test

    # default no-op hooks (walker only dispatches ones overridden)
    def finish_file(self, ctx: FileContext) -> None:
        pass

    def finish_project(self, project: "ProjectState") -> list[Violation]:
        return []


@dataclass
class ProjectState:
    """Cross-file accumulator handed to finish_project hooks."""

    root: str = ""
    files: list[FileContext] = field(default_factory=list)
    # rule-id -> arbitrary accumulated state
    data: dict = field(default_factory=dict)


# ---- shared AST helpers ------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def chain_segments(node: ast.AST) -> list[str]:
    """All identifier segments of an attribute chain, outermost last;
    call/subscript links are skipped but their base is traversed
    (so registry().inc -> ['registry', 'inc'])."""
    parts: list[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, (ast.Call,)):
            node = node.func
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            break
        else:
            break
    return list(reversed(parts))


def call_name(node: ast.Call) -> str:
    """Last segment of the called thing ('' when unresolvable)."""
    segs = chain_segments(node.func)
    return segs[-1] if segs else ""


def kwarg(node: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def is_const(node: Optional[ast.AST], value=...) -> bool:
    if not isinstance(node, ast.Constant):
        return False
    return True if value is ... else node.value is value


# mutation-context detection shared by GL02 (rules_rpc) and the pass-1
# summaries (dataflow) — one home so they can never disagree
MUTATION_NAME_RE = re.compile(
    r"(^|_)(insert|write|put|delete|update|remove|push|apply|store|"
    r"flush|merge)($|_)")
MUTATION_OP_RE = re.compile(
    r"^(insert|write|put|delete|update|remove|push|apply|store|flush)")


def payload_ops(node: ast.Call) -> list[str]:
    """Constant `op` strings found anywhere in the call's payload
    arguments (table RPCs ship {'op': 'insert_many', ...} dicts)."""
    ops = []
    for arg in list(node.args) + [k.value for k in node.keywords
                                  if k.value is not None]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Dict):
                for k, v in zip(sub.keys, sub.values):
                    if is_const(k) and k.value == "op" \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        ops.append(v.value)
    return ops
