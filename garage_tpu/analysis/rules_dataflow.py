"""Flow rules that only exist because of the pass-1 summaries:
GL10 blocking-reachable-from-async, GL11 leaked-budget-on-exception.

GL10 closes GL01's interprocedural hole: GL01 sees `time.sleep` typed
directly inside an `async def`, but the PR 2 regression class more
often hides one helper down (`async def handler` -> `def scan` ->
sqlite). Pass 2 walks the call graph from every async function through
sync project frames (skipping `to_thread` hops, async callees — their
own GL01 problem — and generators, whose call runs nothing) to a
blocking atom, and reports the FULL chain so the fix site is obvious.
Atoms are GL01's hard-I/O list plus the project's sync db seams
(`self.store.iter(...)`, `db.transaction(...)`: receiver matching
store/db/tree/todo/queue/timestamp with a db-verb method, non-awaited)
— digest helpers are deliberately excluded transitively (hashing a
32-byte key two frames down is noise; GL01 still flags digests typed
directly in an async frame).

GL11 is the shape of PR 8's lease-conservation bugs (and Aspirator's
error-path blindness, Yuan et al. OSDI '14): a qos token / lease /
semaphore acquire whose refund sits on the happy path only — any
raise-capable call between acquire and release leaks the budget
permanently. Safe shapes are recognized structurally: `with`-statement
acquires, releases in a `finally:`, the failure-refund idiom
(`except: refund; raise`), acquires with no release at all (plain
admission consumes tokens by design), and acquires whose value
escapes (ownership transferred to the caller)."""

from __future__ import annotations

from .core import ProjectState, Rule, Violation
from .dataflow import (ACQUIRE_METHODS, IO_BLOCKING_CALLS,
                       RELEASE_METHODS)
from .rules_async import BLOCKING_CALLS as _GL01_BLOCKING

# atoms GL10 adds beyond GL01's list: typed DIRECTLY in an async frame
# they are GL10's to report (GL01 would not fire), so inlining a
# flagged helper cannot make the finding disappear
_EXTRA_IO = IO_BLOCKING_CALLS - _GL01_BLOCKING


def _dataflow(project: ProjectState):
    return project.data.get("_dataflow")


def _is_checked_file(project: ProjectState, rel_path: str) -> bool:
    """GL10/GL11 run on production code only (harness files opt into
    the GL04/GL05/GL07 subset, not the flow rules)."""
    for ctx in project.files:
        if ctx.rel_path == rel_path:
            return not ctx.is_test and not ctx.is_harness
    return False


class BlockingReachableFromAsync(Rule):
    id = "GL10"
    name = "blocking-reachable-from-async"
    needs_dataflow = True
    summary = ("a sync helper that blocks (I/O, @blocking_api db seam) "
               "is reachable from an `async def` with no "
               "asyncio.to_thread frame on the path — the event loop "
               "stalls for the whole operation; the report names the "
               "full call chain (since ISSUE 14 iterating an "
               "in-project generator counts, and the db seam is the "
               "@blocking_api annotation where the call resolves, "
               "receiver-name heuristic only for out-of-tree "
               "callables)")
    rationale = (
        "GL01 sees `time.sleep` typed directly in an async def; the "
        "PR 2 regression class more often hides one helper down "
        "(async handler -> def scan -> sqlite). Pass 2 walks the "
        "call graph from every async function through sync project "
        "frames to a blocking atom — GL01's hard-I/O list plus the "
        "project's sync db seams — skipping to_thread hops and async "
        "callees, and reports the FULL chain. ISSUE 14 closed two "
        "holes: `for x in gen(...)` over an in-project generator now "
        "RUNS the body here (reported at the iteration site; a plain "
        "call stays exempt), and db-seam atoms come from the "
        "@blocking_api annotation on db.Db/Tree/Transaction wherever "
        "the call resolves in-project (the store/db/tree receiver-"
        "name heuristic remains as the fallback for calls the graph "
        "cannot resolve). The ISSUE 9 sweep fixed ~30 real on-loop "
        "db calls this found (table sync/gc/queue, resync, k2v poll, "
        "RPC handlers).")
    example_fire = ("def scan(path):\n"
                    "    return sqlite3.connect(path)\n"
                    "async def handler(path):\n"
                    "    return scan(path)      # chain reported")
    example_ok = ("async def handler(path):\n"
                  "    return await asyncio.to_thread(scan, path)")

    def finish_project(self, project: ProjectState) -> list[Violation]:
        df = _dataflow(project)
        if df is None:
            return []
        out: list[Violation] = []
        file_ok: dict[str, bool] = {}
        for fid in sorted(df.graph.functions):
            fn = df.graph.functions[fid]
            if not fn["is_async"]:
                continue
            path = fn["path"]
            if path not in file_ok:
                file_ok[path] = _is_checked_file(project, path)
            if not file_ok[path]:
                continue
            # direct atoms in the async frame itself that GL01 does
            # not own: the db seams (annotation-filtered since
            # ISSUE 14), @blocking_api calls, and the fsync/rename
            # syscalls only GL10's list carries
            for atom in df.graph.atoms_of(fid):
                if atom["kind"] == "db":
                    msg = (f"sync db call `{atom['target']}(...)` "
                           "directly on the event loop; wrap in "
                           "asyncio.to_thread")
                elif atom["kind"] == "api":
                    msg = (f"blocking-annotated `{atom['target']}(...)`"
                           " called directly on the event loop; wrap "
                           "in asyncio.to_thread")
                elif atom["target"] in _EXTRA_IO:
                    msg = (f"blocking `{atom['target']}(...)` directly "
                           "on the event loop; wrap in "
                           "asyncio.to_thread")
                else:
                    continue  # GL01's direct hard-I/O list
                out.append(self._violation(path, atom["line"], fn, msg))
            reported: set[str] = set()
            for chain in df.graph.blocking_chains(fid):
                atom = chain[-1]
                frames = chain[:-1]
                atom_fid = frames[-1][0]
                if atom_fid in reported:
                    continue
                reported.add(atom_fid)
                first_rec = frames[0][1]
                hops = " -> ".join(
                    [fn["qualname"]]
                    + [df.graph.functions[cid]["qualname"]
                       for cid, _ in frames])
                atom_fn = df.graph.functions[atom_fid]
                out.append(self._violation(
                    path, first_rec["line"], fn,
                    f"blocking `{atom['target']}` reachable from this "
                    f"async frame with no to_thread hop: {hops} "
                    f"(atom at {atom_fn['path']}:{atom['line']}); move "
                    "the sync frame into asyncio.to_thread",
                    end_line=first_rec.get("end_line")))
        return out

    def _violation(self, path: str, line: int, fn: dict, msg: str,
                   end_line=None) -> Violation:
        v = Violation(rule=self.id, path=path, line=line, col=0,
                      message=msg, context=fn["qualname"])
        v._end_line = end_line  # type: ignore[attr-defined]
        return v


class LeakedBudgetOnException(Rule):
    id = "GL11"
    name = "leaked-budget-on-exception"
    needs_dataflow = True
    summary = ("qos token / lease / semaphore acquire whose refund or "
               "release is not on every exit path — a raise between "
               "acquire and the happy-path release leaks the budget "
               "(PR 8's lease-conservation bug class); cross-function "
               "since ISSUE 14: an acquire here released in a callee "
               "(or handed out by an acquiring helper) settles through "
               "the call graph instead of mis-reporting")
    rationale = (
        "The exact shape of PR 8's lease-conservation bugs (and "
        "Aspirator's error-path blindness): acquire, do raise-capable "
        "work, release — the release never runs on the exception "
        "path and the budget leaks permanently. Recognized-safe "
        "shapes: `with` acquires, release in a finally:, the "
        "failure-refund idiom (except: refund; raise), acquires with "
        "no release at all (plain admission consumes by design), and "
        "acquires whose value escapes (ownership transferred). Since "
        "ISSUE 14 acquire/release facts settle ACROSS call-graph "
        "edges to a fixpoint: a release inside a helper invoked from "
        "a finally: is exception-safe (no false positive), a helper "
        "that acquires and returns makes its CALLER the owner (the "
        "happy-path-only release there is a real leak — no false "
        "negative). This is the shape of BudgetLeaseBroker "
        "revoke/renew and the feeder's abort paths. Since ISSUE 20 "
        "the raise-capable-call check is path-sensitive over the "
        "pass-1 CFG: a call in a dead except handler or a branch "
        "that never reaches the release does not count.")
    example_fire = ("lease = self._rent(n)   # helper acquires+returns\n"
                    "resp = await upstream()  # raise leaks the lease\n"
                    "lease.release()")
    example_ok = ("tok = await bucket.acquire(n)\n"
                  "try:\n    resp = await upstream()\n"
                  "finally:\n    self._give_back(tok)  "
                  "# releases in the callee")

    # fixpoint iteration cap (call chains deeper than this are noise)
    _MAX_ROUNDS = 8

    def finish_project(self, project: ProjectState) -> list[Violation]:
        df = _dataflow(project)
        if df is None:
            return []
        g = df.graph
        fns = g.functions
        rel_params, rel_attrs = self._release_facts(g)
        acq_ret = self._acquire_returning(g)

        out: list[Violation] = []
        file_ok: dict[str, bool] = {}
        for fid in sorted(fns):
            fn = fns[fid]
            path = fn["path"]
            if path not in file_ok:
                file_ok[path] = _is_checked_file(project, path)
            if not file_ok[path]:
                continue
            ret_names = set(fn["ret_names"])
            acquires = []
            for a in fn["acquires"]:
                if a["in_with"]:
                    continue
                acquires.append((a["line"], a["recv"],
                                 {a["recv"]} | set(a["names"]),
                                 set(a["names"])))
            for callee, rec in g.edges_from(fid):
                # synthetic acquire: the callee acquires and hands the
                # resource back — this frame is now the owner
                if callee in acq_ret and rec.get("bound") \
                        and not rec["via_thread"]:
                    bound = set(rec["bound"])
                    acquires.append(
                        (rec["line"], rec["name"], set(bound), bound))
            for line, recv, match_names, bound in acquires:
                if bound & ret_names:
                    continue  # ownership passed to OUR caller
                evs = self._release_events(g, fid, fn, match_names,
                                           rel_params, rel_attrs)
                if not evs:
                    continue  # plain admission consumes by design
                if any(ctx == "finally" for _, ctx in evs):
                    continue
                plain = [(ln, ctx) for ln, ctx in evs
                         if ctx != "except"]
                if not plain:
                    continue  # except-refund-reraise idiom
                after = [ln for ln, _ in plain if ln > line]
                if not after:
                    continue
                rel_line = min(after)
                risky = self._risky_between(fn, line, rel_line)
                if risky is None:
                    continue
                out.append(Violation(
                    rule=self.id, path=path, line=line, col=0,
                    message=(
                        f"`{recv}` acquire here is released at line "
                        f"{rel_line} only on the happy path — the call "
                        f"at line {risky} can raise and leak the "
                        "budget; release in a finally: (or refund in "
                        "an except: ... raise)"),
                    context=fn["qualname"]))
        return out

    # ---- cross-function facts (fixpoints over summaries) ---------------

    def _release_facts(self, g) -> tuple[dict, dict]:
        """rel_params[fid] = own params the function releases (itself
        or by passing them into a releasing callee); rel_attrs[fid] =
        receiver names it releases, transitively through self-calls
        (the `finally: self._cleanup()` shape)."""
        fns = g.functions
        rel_params = {}
        rel_attrs = {}
        for fid, fn in fns.items():
            params = set(fn["params"])
            rel_params[fid] = {r["recv"] for r in fn["releases"]
                               if r["recv"] in params}
            rel_attrs[fid] = {r["recv"] for r in fn["releases"]}
        for _ in range(self._MAX_ROUNDS):
            changed = False
            for fid, fn in fns.items():
                params = set(fn["params"])
                for callee, rec in g.edges_from(fid):
                    if rec["via_thread"]:
                        continue
                    for n, _ln in self._released_args(
                            g, fid, callee, rec, rel_params):
                        if n in params and n not in rel_params[fid]:
                            rel_params[fid].add(n)
                            changed = True
                    if rec["ref"][0] == "self":
                        new = rel_attrs[callee] - rel_attrs[fid]
                        if new:
                            rel_attrs[fid] |= new
                            changed = True
            if not changed:
                break
        return rel_params, rel_attrs

    def _released_args(self, g, fid, callee, rec, rel_params):
        """Names this call passes into parameters the callee releases."""
        shift = g.bound_call(fid, rec)
        for pos, ad in enumerate(rec["args"]):
            if not ad or "n" not in ad:
                continue
            pname = g.param_index(callee, pos, shift)
            if pname and pname in rel_params.get(callee, ()):
                yield ad["n"], rec["line"]
        for k, ad in rec.get("kw", {}).items():
            if ad and "n" in ad and k in rel_params.get(callee, ()):
                yield ad["n"], rec["line"]

    def _acquire_returning(self, g) -> set:
        """Functions that hand an acquired resource to their caller:
        return a name bound from an acquire, return the acquire call
        itself, or return the result of another acquire-returning
        function (fixpoint)."""
        fns = g.functions
        acq_ret = set()
        for fid, fn in fns.items():
            ret_names = set(fn["ret_names"])
            if any(set(a["names"]) & ret_names for a in fn["acquires"]):
                acq_ret.add(fid)
            if any(rec["name"] in ACQUIRE_METHODS and rec.get("in_ret")
                   for rec in fn["calls"]):
                acq_ret.add(fid)
        for _ in range(self._MAX_ROUNDS):
            changed = False
            for fid, fn in fns.items():
                if fid in acq_ret:
                    continue
                ret_names = set(fn["ret_names"])
                for callee, rec in g.edges_from(fid):
                    if callee not in acq_ret or rec["via_thread"]:
                        continue
                    if rec.get("in_ret") \
                            or set(rec.get("bound", ())) & ret_names:
                        acq_ret.add(fid)
                        changed = True
                        break
            if not changed:
                break
        return acq_ret

    def _release_events(self, g, fid, fn, match_names,
                        rel_params, rel_attrs) -> list[tuple[int, str]]:
        evs = [(r["line"], r["ctx"]) for r in fn["releases"]
               if r["recv"] in match_names]
        for callee, rec in g.edges_from(fid):
            if rec["via_thread"]:
                continue
            for n, ln in self._released_args(g, fid, callee, rec,
                                             rel_params):
                if n in match_names:
                    evs.append((ln, rec["ctx"]))
            if rec["ref"][0] == "self" \
                    and rel_attrs.get(callee, set()) & match_names:
                evs.append((rec["line"], rec["ctx"]))
        return sorted(set(evs))

    def _risky_between(self, fn, lo: int, hi: int):
        """Line of a raise-capable call between acquire (lo) and
        release (hi). Path-sensitive since ISSUE 20: with a CFG in the
        summary the call must also sit on some control-flow path from
        the acquire's block to the release's block — a call in a dead
        except handler (or a sibling branch that never reaches the
        release) no longer counts. Summaries without a CFG fall back
        to the textual check."""
        on_path = self._on_path_lines(fn, lo, hi)
        for rec in fn["calls"]:
            if lo < rec["line"] < hi \
                    and rec["name"] not in RELEASE_METHODS \
                    and (on_path is None or rec["line"] in on_path):
                return rec["line"]
        return None

    @staticmethod
    def _on_path_lines(fn, lo: int, hi: int):
        """Call lines inside blocks on some CFG path from the block
        containing line `lo` to the block containing line `hi`
        (forward-reachable from the start AND backward-reachable from
        the end). None when the CFG cannot anchor both lines."""
        cfg = fn.get("cfg")
        if not cfg:
            return None
        blocks = {b["id"]: b for b in cfg["blocks"]}
        start = end = None
        for b in cfg["blocks"]:
            if start is None and (lo in b["lines"] or lo in b["calls"]):
                start = b["id"]
            if end is None and (hi in b["lines"] or hi in b["calls"]):
                end = b["id"]
        if start is None or end is None:
            return None
        fwd = {start}
        work = [start]
        while work:
            for s in blocks[work.pop()]["succ"]:
                if s != -1 and s not in fwd:
                    fwd.add(s)
                    work.append(s)
        preds: dict[int, list[int]] = {}
        for b in cfg["blocks"]:
            for s in b["succ"]:
                preds.setdefault(s, []).append(b["id"])
        bwd = {end}
        work = [end]
        while work:
            for p in preds.get(work.pop(), ()):
                if p not in bwd:
                    bwd.add(p)
                    work.append(p)
        lines: set[int] = set()
        for bid in fwd & bwd:
            lines.update(blocks[bid]["calls"])
        return lines
