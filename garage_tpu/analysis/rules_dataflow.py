"""Flow rules that only exist because of the pass-1 summaries:
GL10 blocking-reachable-from-async, GL11 leaked-budget-on-exception.

GL10 closes GL01's interprocedural hole: GL01 sees `time.sleep` typed
directly inside an `async def`, but the PR 2 regression class more
often hides one helper down (`async def handler` -> `def scan` ->
sqlite). Pass 2 walks the call graph from every async function through
sync project frames (skipping `to_thread` hops, async callees — their
own GL01 problem — and generators, whose call runs nothing) to a
blocking atom, and reports the FULL chain so the fix site is obvious.
Atoms are GL01's hard-I/O list plus the project's sync db seams
(`self.store.iter(...)`, `db.transaction(...)`: receiver matching
store/db/tree/todo/queue/timestamp with a db-verb method, non-awaited)
— digest helpers are deliberately excluded transitively (hashing a
32-byte key two frames down is noise; GL01 still flags digests typed
directly in an async frame).

GL11 is the shape of PR 8's lease-conservation bugs (and Aspirator's
error-path blindness, Yuan et al. OSDI '14): a qos token / lease /
semaphore acquire whose refund sits on the happy path only — any
raise-capable call between acquire and release leaks the budget
permanently. Safe shapes are recognized structurally: `with`-statement
acquires, releases in a `finally:`, the failure-refund idiom
(`except: refund; raise`), acquires with no release at all (plain
admission consumes tokens by design), and acquires whose value
escapes (ownership transferred to the caller)."""

from __future__ import annotations

from .core import ProjectState, Rule, Violation
from .dataflow import IO_BLOCKING_CALLS
from .rules_async import BLOCKING_CALLS as _GL01_BLOCKING

# atoms GL10 adds beyond GL01's list: typed DIRECTLY in an async frame
# they are GL10's to report (GL01 would not fire), so inlining a
# flagged helper cannot make the finding disappear
_EXTRA_IO = IO_BLOCKING_CALLS - _GL01_BLOCKING


def _dataflow(project: ProjectState):
    return project.data.get("_dataflow")


def _is_checked_file(project: ProjectState, rel_path: str) -> bool:
    """GL10/GL11 run on production code only (harness files opt into
    the GL04/GL05/GL07 subset, not the flow rules)."""
    for ctx in project.files:
        if ctx.rel_path == rel_path:
            return not ctx.is_test and not ctx.is_harness
    return False


class BlockingReachableFromAsync(Rule):
    id = "GL10"
    name = "blocking-reachable-from-async"
    needs_dataflow = True
    summary = ("a sync helper that blocks (I/O, sqlite/LSM db seam) is "
               "reachable from an `async def` with no asyncio.to_thread "
               "frame on the path — the event loop stalls for the whole "
               "operation; the report names the full call chain")
    rationale = (
        "GL01 sees `time.sleep` typed directly in an async def; the "
        "PR 2 regression class more often hides one helper down "
        "(async handler -> def scan -> sqlite). Pass 2 walks the "
        "call graph from every async function through sync project "
        "frames to a blocking atom — GL01's hard-I/O list plus the "
        "project's sync db seams (store/db/tree/todo/queue receivers "
        "with a db-verb method) — skipping to_thread hops, async "
        "callees and generators, and reports the FULL chain. The "
        "ISSUE 9 sweep fixed ~30 real on-loop db calls this found "
        "(table sync/gc/queue, resync, k2v poll, RPC handlers).")
    example_fire = ("def scan(path):\n"
                    "    return sqlite3.connect(path)\n"
                    "async def handler(path):\n"
                    "    return scan(path)      # chain reported")
    example_ok = ("async def handler(path):\n"
                  "    return await asyncio.to_thread(scan, path)")

    def finish_project(self, project: ProjectState) -> list[Violation]:
        df = _dataflow(project)
        if df is None:
            return []
        out: list[Violation] = []
        file_ok: dict[str, bool] = {}
        for fid in sorted(df.graph.functions):
            fn = df.graph.functions[fid]
            if not fn["is_async"]:
                continue
            path = fn["path"]
            if path not in file_ok:
                file_ok[path] = _is_checked_file(project, path)
            if not file_ok[path]:
                continue
            # direct atoms in the async frame itself that GL01 does
            # not own: the db seams, and the fsync/rename syscalls
            # only GL10's list carries
            for atom in fn["blocking"]:
                if atom["kind"] == "db":
                    msg = (f"sync db call `{atom['target']}(...)` "
                           "directly on the event loop; wrap in "
                           "asyncio.to_thread")
                elif atom["target"] in _EXTRA_IO:
                    msg = (f"blocking `{atom['target']}(...)` directly "
                           "on the event loop; wrap in "
                           "asyncio.to_thread")
                else:
                    continue  # GL01's direct hard-I/O list
                out.append(self._violation(path, atom["line"], fn, msg))
            reported: set[str] = set()
            for chain in df.graph.blocking_chains(fid):
                atom = chain[-1]
                frames = chain[:-1]
                atom_fid = frames[-1][0]
                if atom_fid in reported:
                    continue
                reported.add(atom_fid)
                first_rec = frames[0][1]
                hops = " -> ".join(
                    [fn["qualname"]]
                    + [df.graph.functions[cid]["qualname"]
                       for cid, _ in frames])
                atom_fn = df.graph.functions[atom_fid]
                out.append(self._violation(
                    path, first_rec["line"], fn,
                    f"blocking `{atom['target']}` reachable from this "
                    f"async frame with no to_thread hop: {hops} "
                    f"(atom at {atom_fn['path']}:{atom['line']}); move "
                    "the sync frame into asyncio.to_thread",
                    end_line=first_rec.get("end_line")))
        return out

    def _violation(self, path: str, line: int, fn: dict, msg: str,
                   end_line=None) -> Violation:
        v = Violation(rule=self.id, path=path, line=line, col=0,
                      message=msg, context=fn["qualname"])
        v._end_line = end_line  # type: ignore[attr-defined]
        return v


class LeakedBudgetOnException(Rule):
    id = "GL11"
    name = "leaked-budget-on-exception"
    needs_dataflow = True
    summary = ("qos token / lease / semaphore acquire whose refund or "
               "release is not on every exit path — a raise between "
               "acquire and the happy-path release leaks the budget "
               "(PR 8's lease-conservation bug class); move the release "
               "into a finally: or the except-reraise refund idiom")
    rationale = (
        "The exact shape of PR 8's lease-conservation bugs (and "
        "Aspirator's error-path blindness): acquire, do raise-capable "
        "work, release — the release never runs on the exception "
        "path and the budget leaks permanently. Recognized-safe "
        "shapes: `with` acquires, release in a finally:, the "
        "failure-refund idiom (except: refund; raise), acquires with "
        "no release at all (plain admission consumes by design), and "
        "acquires whose value escapes (ownership transferred).")
    example_fire = ("tok = await bucket.acquire(n)\n"
                    "resp = await upstream()     # raise leaks tok\n"
                    "bucket.refund(n)")
    example_ok = ("tok = await bucket.acquire(n)\n"
                  "try:\n    resp = await upstream()\n"
                  "finally:\n    bucket.refund(n)")

    def finish_project(self, project: ProjectState) -> list[Violation]:
        df = _dataflow(project)
        if df is None:
            return []
        out: list[Violation] = []
        file_ok: dict[str, bool] = {}
        for fid in sorted(df.graph.functions):
            fn = df.graph.functions[fid]
            path = fn["path"]
            if path not in file_ok:
                file_ok[path] = _is_checked_file(project, path)
            if not file_ok[path]:
                continue
            for leak in fn["leaks"]:
                v = Violation(
                    rule=self.id, path=path, line=leak["line"], col=0,
                    message=(
                        f"`{leak['recv']}` acquire here is released at "
                        f"line {leak['release_line']} only on the happy "
                        f"path — the call at line {leak['risky_line']} "
                        "can raise and leak the budget; release in a "
                        "finally: (or refund in an except: ... raise)"),
                    context=fn["qualname"])
                out.append(v)
        return out
