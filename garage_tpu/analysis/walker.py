"""Single-pass multi-rule AST walker with scope / async-context tracking.

One recursive traversal per file; every rule's hooks are dispatched
from that same pass (Infer/RacerD-style compositional per-file
analysis — cross-file rules accumulate into ProjectState and settle in
finish_project). The walker owns ALL context bookkeeping: function and
class stacks, async-ness, async-with-lock frames, and a per-function
scratch dict with the cheap "dataflow" rules need (argument names,
assigned locals, RequestStrategy bindings) so each rule stays a few
lines of pattern matching.
"""

from __future__ import annotations

import ast
import os

from .core import (FileContext, META_RULE, ProjectState, Rule, Violation,
                   call_name, chain_segments)

# directory/file names never scanned (fixtures feed the self-tests
# violations on purpose)
EXCLUDE_DIRS = {"__pycache__", ".git", "fixtures"}

# harness files (FileContext.is_harness: clusterbox / conftest / bench)
# run exactly this subset — harness code orphaning tasks or swallowing
# exceptions silently corrupts chaos-soak verdicts, but the
# production-invariant rules (async hygiene, hedge/SSE-C flow, config
# drift) do not apply to driver code (ISSUE 9 satellite)
HARNESS_RULES = {"GL04", "GL05", "GL07"}

LOCK_HINT = "lock"


def _looks_like_lock(expr: ast.AST) -> bool:
    """The context expression of an `async with` names a lock: any
    identifier segment containing 'lock' (self._require_lock,
    write_lock(), state.lock)."""
    return any(LOCK_HINT in seg.lower() for seg in chain_segments(expr))


def _function_meta(node: ast.AST) -> dict:
    """Scratch facts about one function body, collected once on entry:
    names bound (args + assignment targets), and simple
    `name = RequestStrategy(...)` bindings so call sites can resolve a
    locally built strategy."""
    args: set[str] = set()
    assigned: set[str] = set()
    strategies: dict[str, ast.Call] = {}
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = node.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            args.add(arg.arg)
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    assigned.add(t.id)
                    if isinstance(sub.value, ast.Call) and \
                            call_name(sub.value) == "RequestStrategy":
                        strategies[t.id] = sub.value
        elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(sub.target, ast.Name):
                assigned.add(sub.target.id)
        elif isinstance(sub, (ast.For, ast.AsyncFor)):
            if isinstance(sub.target, ast.Name):
                assigned.add(sub.target.id)
        elif isinstance(sub, (ast.With, ast.AsyncWith)):
            for item in sub.items:
                if isinstance(item.optional_vars, ast.Name):
                    assigned.add(item.optional_vars.id)
    return {"args": args, "assigned": assigned, "strategies": strategies}


class FileAnalyzer:
    """Runs every applicable rule over one file in a single traversal."""

    def __init__(self, rules: list[Rule]):
        self.rules = rules

    def run(self, ctx: FileContext) -> None:
        """Single traversal; waiver application is the CALLER's step
        (after cross-file rules settle, so their violations are
        waivable too)."""
        if ctx.is_harness:
            rules = [r for r in self.rules if r.id in HARNESS_RULES]
        else:
            rules = [r for r in self.rules if r.applies_to(ctx)]
        if not rules:
            return
        hooks = {
            "call": [r for r in rules if hasattr(r, "on_call")],
            "await": [r for r in rules if hasattr(r, "on_await")],
            "expr": [r for r in rules if hasattr(r, "on_expr_stmt")],
            "except": [r for r in rules if hasattr(r, "on_except")],
            "function": [r for r in rules if hasattr(r, "on_function")],
            "attribute": [r for r in rules if hasattr(r, "on_attribute")],
        }
        self._visit(ctx.tree, ctx, hooks)
        for r in rules:
            r.finish_file(ctx)

    def _visit(self, node: ast.AST, ctx: FileContext, hooks: dict) -> None:
        push_func = push_class = push_lock = False
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ctx.func_stack.append(
                (node, node.name, isinstance(node, ast.AsyncFunctionDef),
                 _function_meta(node)))
            push_func = True
            for r in hooks["function"]:
                r.on_function(node, ctx)
        elif isinstance(node, ast.Lambda):
            # a lambda body is a sync scope (GL01's to_thread escape)
            ctx.func_stack.append((node, "<lambda>", False, {}))
            push_func = True
        elif isinstance(node, ast.ClassDef):
            ctx.class_stack.append(node.name)
            push_class = True
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            # sync `with lock():` counts too (ISSUE 9): a threading
            # lock held across an await inside an async frame blocks
            # every other task on the loop exactly like an async lock
            if any(_looks_like_lock(item.context_expr)
                   for item in node.items):
                ctx.lock_stack.append(node)
                push_lock = True
        elif isinstance(node, ast.Call):
            for r in hooks["call"]:
                r.on_call(node, ctx)
        elif isinstance(node, ast.Await):
            for r in hooks["await"]:
                r.on_await(node, ctx)
        elif isinstance(node, ast.Expr):
            for r in hooks["expr"]:
                r.on_expr_stmt(node, ctx)
        elif isinstance(node, ast.ExceptHandler):
            for r in hooks["except"]:
                r.on_except(node, ctx)
        elif isinstance(node, ast.Attribute):
            for r in hooks["attribute"]:
                r.on_attribute(node, ctx)

        for child in ast.iter_child_nodes(node):
            self._visit(child, ctx, hooks)

        if push_func:
            ctx.func_stack.pop()
        if push_class:
            ctx.class_stack.pop()
        if push_lock:
            ctx.lock_stack.pop()


def iter_python_files(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDE_DIRS)
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return out


def _needs_dataflow(rules: list[Rule]) -> bool:
    return any(getattr(r, "needs_dataflow", False) for r in rules)


def _build_dataflow(project: ProjectState, rules: list[Rule]) -> None:
    """Pass 1 of the interprocedural engine (ISSUE 9): per-function
    summaries + call graph, shared by every needs_dataflow rule.
    Cache-aware: project.data["summary_cache"] (loaded by the CLI from
    --summary-cache) short-circuits the summary walk for files whose
    hash is unchanged."""
    if not _needs_dataflow(rules):
        return
    files = [c for c in project.files if c.tree is not None]
    if "_dataflow" in project.data \
            and project.data.get("_dataflow_files") == len(files):
        return
    from .dataflow import DataflowState

    project.data["_dataflow"] = DataflowState(
        files, summary_cache=project.data.get("summary_cache"))
    project.data["_dataflow_files"] = len(files)


def _settle_project(project: ProjectState, rules: list[Rule],
                    restricted: bool = False) -> list[Violation]:
    """Run cross-file rules, attach their violations to the owning
    file context (so they are waivable at the line they land on), then
    apply waivers everywhere. Returns violations that matched no
    scanned file (stray). `restricted` marks a --rules subset run:
    waivers for rules that did not run are exempt from the staleness
    check (a full run still checks every waiver, typos included)."""
    _build_dataflow(project, rules)
    by_rel = {c.rel_path: c for c in project.files}
    # idempotent under re-settling: a repeated finish_project (shared
    # project across analyze_source calls) must not duplicate findings
    seen = {v.key() for c in project.files for v in c.violations}
    stray: list[Violation] = []
    for r in rules:
        for v in r.finish_project(project):
            if v.key() in seen:
                continue
            seen.add(v.key())
            ctx = by_rel.get(v.path)
            if ctx is not None:
                ctx.violations.append(v)
            else:
                stray.append(v)
    active = {r.id for r in rules} if restricted else None
    for c in project.files:
        c.apply_waivers(active_rules=active)
    return stray


def analyze_source(source: str, rules: list[Rule],
                   rel_path: str = "<memory>.py",
                   project: ProjectState | None = None) -> FileContext:
    """Analyze one in-memory module (the fixture-test entry point)
    through the FULL pipeline, cross-file/dataflow rules included —
    the mini-project contains just this file. Parse failures surface
    as a GL00 violation, never an exception."""
    if project is None:
        project = ProjectState()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        ctx = FileContext(rel_path, rel_path, "", ast.Module(body=[],
                                                             type_ignores=[]))
        ctx.tree = None
        ctx.violations.append(Violation(
            rule=META_RULE, path=rel_path, line=e.lineno or 1,
            col=e.offset or 0, message=f"unparseable: {e.msg}"))
        project.files.append(ctx)
        return ctx
    ctx = FileContext(rel_path, rel_path, source, tree)
    FileAnalyzer(rules).run(ctx)
    project.files.append(ctx)
    _settle_project(project, rules)
    return ctx


def analyze_paths(paths: list[str], rules: list[Rule],
                  root: str | None = None,
                  data: dict | None = None,
                  restricted: bool = False) -> tuple[list[Violation],
                                                     ProjectState]:
    """Analyze every .py under `paths`; returns (violations, project).
    Violations include waived/baselined-candidate ones — the caller
    filters on .active after baseline matching. `data` seeds
    ProjectState.data (e.g. readme_text for GL08)."""
    root = os.path.abspath(root or os.path.commonpath(
        [os.path.abspath(p) for p in paths]) if paths else ".")
    if os.path.isfile(root):
        root = os.path.dirname(root)
    project = ProjectState(root=root, data=dict(data or {}))
    for path in iter_python_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        try:
            with open(ap, "r", encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            project.files.append(_error_ctx(rel, f"unreadable: {e}"))
            continue
        try:
            tree = ast.parse(source, filename=ap)
        except SyntaxError as e:
            project.files.append(_error_ctx(
                rel, f"unparseable: {e.msg}", e.lineno or 1))
            continue
        ctx = FileContext(ap, rel, source, tree)
        FileAnalyzer(rules).run(ctx)
        project.files.append(ctx)
    # cross-file rules settle BEFORE waivers, so their violations are
    # waivable at the line they land on (e.g. a config.py field read
    # only via getattr carries its own inline waiver)
    stray = _settle_project(project, rules, restricted=restricted)
    violations = [v for c in project.files for v in c.violations] + stray
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations, project


def _error_ctx(rel: str, msg: str, line: int = 1) -> FileContext:
    ctx = FileContext(rel, rel, "", ast.Module(body=[], type_ignores=[]))
    ctx.violations.append(Violation(rule=META_RULE, path=rel, line=line,
                                    col=0, message=msg))
    return ctx
