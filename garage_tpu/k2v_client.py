"""K2V client SDK: a standalone synchronous client for the K2V API.

Ref parity: src/k2v-client/lib.rs:59-341 (the reference ships a Rust
SDK crate; this is its Python equivalent, self-contained — its own
SigV4 signer with scope service "k2v", stdlib HTTP only, usable from
scripts without importing the server packages).

    c = K2vClient("127.0.0.1", 3904, "bucket", key_id, secret)
    c.insert_item("pk", "sk", b"value")
    val = c.read_item("pk", "sk")          # -> K2vValue
    c.insert_item("pk", "sk", b"v2", causality=val.causality)
    c.delete_item("pk", "sk", causality=...)
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import http.client
import json
from dataclasses import dataclass
from typing import Optional
from urllib.parse import quote

CAUSALITY_HEADER = "x-garage-causality-token"


@dataclass
class K2vValue:
    """One read result: the concurrent values (None = delete marker)
    and the causality token to echo on the next write."""

    causality: str
    values: list[Optional[bytes]]

    @property
    def value(self) -> Optional[bytes]:
        live = [v for v in self.values if v is not None]
        return live[0] if live else None


@dataclass
class PartitionInfo:
    pk: str
    entries: int
    conflicts: int
    values: int
    bytes: int


class K2vError(Exception):
    def __init__(self, status: int, code: str, message: str):
        self.status = status
        self.code = code
        super().__init__(f"{status} {code}: {message}")


class K2vClient:
    def __init__(self, host: str, port: int, bucket: str, key_id: str,
                 secret: str, region: str = "garage"):
        self.host, self.port = host, port
        self.bucket = bucket
        self.key_id, self.secret = key_id, secret
        self.region = region

    # ---- signing (SigV4, service "k2v") --------------------------------

    def _sign(self, method: str, path: str, query: list[tuple[str, str]],
              headers: dict[str, str], body: bytes) -> dict[str, str]:
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date = now.strftime("%Y%m%d")
        payload_hash = hashlib.sha256(body).hexdigest()
        headers = {k.lower(): v for k, v in headers.items()}
        headers["host"] = f"{self.host}:{self.port}"
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_hash
        signed = sorted(headers)
        cq = "&".join(
            f"{quote(k, safe='-_.~')}={quote(v, safe='-_.~')}"
            for k, v in sorted(query))
        creq = "\n".join([
            method, quote(path, safe="/-_.~"), cq,
            "".join(f"{k}:{headers[k].strip()}\n" for k in signed),
            ";".join(signed), payload_hash,
        ])
        scope = f"{date}/{self.region}/k2v/aws4_request"
        sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(creq.encode()).hexdigest()])
        k = b"AWS4" + self.secret.encode()
        for part in (date, self.region, "k2v", "aws4_request"):
            k = hmac.new(k, part.encode(), hashlib.sha256).digest()
        sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.key_id}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")
        return headers

    def _req(self, method: str, path: str,
             query: Optional[list[tuple[str, str]]] = None,
             headers: Optional[dict[str, str]] = None,
             body: bytes = b"", timeout: float = 330.0):
        query = query or []
        headers = self._sign(method, path, query, headers or {}, body)
        qs = "&".join(f"{quote(k, safe='-_.~')}={quote(v, safe='-_.~')}"
                      for k, v in query)
        url = path + ("?" + qs if qs else "")
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        try:
            conn.request(method, url, body=body, headers=headers)
            r = conn.getresponse()
            return r.status, {k.lower(): v for k, v in r.getheaders()}, \
                r.read()
        finally:
            conn.close()

    @staticmethod
    def _raise(status: int, body: bytes):
        try:
            err = json.loads(body.decode())
            raise K2vError(status, err.get("code", "?"),
                           err.get("message", ""))
        except (ValueError, UnicodeDecodeError, AttributeError):
            raise K2vError(status, "?", body[:200].decode("utf-8",
                                                          "replace"))

    # ---- item ops (ref: k2v-client/lib.rs) -----------------------------

    def read_item(self, pk: str, sk: str) -> K2vValue:
        st, hdrs, body = self._req(
            "GET", f"/{self.bucket}/{quote(pk, safe='')}",
            query=[("sort_key", sk)],
            headers={"accept": "application/json"})
        if st != 200:
            self._raise(st, body)
        vals = [None if v is None else base64.b64decode(v)
                for v in json.loads(body.decode())]
        return K2vValue(hdrs[CAUSALITY_HEADER], vals)

    def insert_item(self, pk: str, sk: str, value: bytes,
                    causality: Optional[str] = None) -> None:
        headers = {CAUSALITY_HEADER: causality} if causality else {}
        st, _, body = self._req(
            "PUT", f"/{self.bucket}/{quote(pk, safe='')}",
            query=[("sort_key", sk)], headers=headers, body=value)
        if st not in (200, 204):
            self._raise(st, body)

    def delete_item(self, pk: str, sk: str, causality: str) -> None:
        st, _, body = self._req(
            "DELETE", f"/{self.bucket}/{quote(pk, safe='')}",
            query=[("sort_key", sk)],
            headers={CAUSALITY_HEADER: causality})
        if st not in (200, 204):
            self._raise(st, body)

    def poll_item(self, pk: str, sk: str, causality: str,
                  timeout: float = 300.0) -> Optional[K2vValue]:
        """Long-poll until a newer version exists; None on timeout."""
        st, hdrs, body = self._req(
            "GET", f"/{self.bucket}/{quote(pk, safe='')}",
            query=[("sort_key", sk), ("causality_token", causality),
                   ("timeout", str(timeout))],
            headers={"accept": "application/json"},
            timeout=timeout + 30.0)
        if st == 304:
            return None
        if st != 200:
            self._raise(st, body)
        vals = [None if v is None else base64.b64decode(v)
                for v in json.loads(body.decode())]
        return K2vValue(hdrs[CAUSALITY_HEADER], vals)

    def poll_range(self, pk: str, seen_marker: Optional[str] = None,
                   prefix: Optional[str] = None,
                   start: Optional[str] = None, end: Optional[str] = None,
                   timeout: float = 300.0):
        """Long-poll a sort-key range; -> (items, seen_marker) or None
        on timeout. Items are dicts {sk, ct, v: [bytes|None]}."""
        spec = {"timeout": timeout}
        if seen_marker:
            spec["seenMarker"] = seen_marker
        if prefix is not None:
            spec["prefix"] = prefix
        if start is not None:
            spec["start"] = start
        if end is not None:
            spec["end"] = end
        st, _, body = self._req(
            "POST", f"/{self.bucket}/{quote(pk, safe='')}",
            query=[("poll_range", "")],
            body=json.dumps(spec).encode(), timeout=timeout + 30.0)
        if st == 304:
            return None
        if st != 200:
            self._raise(st, body)
        data = json.loads(body.decode())
        items = [{"sk": i["sk"], "ct": i["ct"],
                  "v": [None if v is None else base64.b64decode(v)
                        for v in i["v"]]}
                 for i in data["items"]]
        return items, data["seenMarker"]

    # ---- index / batch -------------------------------------------------

    def read_index(self, prefix: Optional[str] = None,
                   limit: Optional[int] = None) -> list[PartitionInfo]:
        q = []
        if prefix is not None:
            q.append(("prefix", prefix))
        if limit is not None:
            q.append(("limit", str(limit)))
        st, _, body = self._req("GET", f"/{self.bucket}", query=q)
        if st != 200:
            self._raise(st, body)
        data = json.loads(body.decode())
        return [PartitionInfo(p["pk"], p["entries"], p["conflicts"],
                              p["values"], p["bytes"])
                for p in data["partitionKeys"]]

    def insert_batch(self, items: list[tuple]) -> None:
        """items: [(pk, sk, value-bytes-or-None, causality-or-None)]."""
        payload = [{
            "pk": pk, "sk": sk,
            "v": base64.b64encode(v).decode() if v is not None else None,
            "ct": ct,
        } for pk, sk, v, ct in items]
        st, _, body = self._req("POST", f"/{self.bucket}",
                                body=json.dumps(payload).encode())
        if st not in (200, 204):
            self._raise(st, body)

    def read_batch(self, queries: list[dict]) -> list[dict]:
        st, _, body = self._req("POST", f"/{self.bucket}",
                                query=[("search", "")],
                                body=json.dumps(queries).encode())
        if st != 200:
            self._raise(st, body)
        return json.loads(body.decode())

    def delete_batch(self, queries: list[dict]) -> list[dict]:
        st, _, body = self._req("POST", f"/{self.bucket}",
                                query=[("delete", "")],
                                body=json.dumps(queries).encode())
        if st != 200:
            self._raise(st, body)
        return json.loads(body.decode())
