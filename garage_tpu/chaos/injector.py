"""Deterministic, seeded fault injection behind named seams.

Yuan et al. (OSDI 2014, "Simple Testing Can Prevent Most Critical
Failures") found that the majority of catastrophic distributed-system
failures are triggered by error-handling paths that were never
exercised. This module makes those paths exercisable: the net, disk and
rpc layers each carry a named injection seam that is a no-op unless a
fault is armed, and tests/benches arm precisely-scoped faults against
them.

Design rules:

- **No-op fast path.** Every seam starts with `if injector.ACTIVE is
  None: <nothing>` — one module-attribute load and an identity check.
  `ACTIVE` is only non-None while at least one fault is armed, so a
  production node that never touches the chaos API pays a single
  pointer compare per seam crossing.
- **Deterministic.** One `random.Random(seed)` drives every probability
  draw and every bit-rot position. Under a single event loop the draw
  order is the event order, so a fixed seed + fixed workload replays
  the same faults. Faults with `prob=1.0` and a `count` budget are
  deterministic regardless of draw order.
- **Scoped.** A fault fires only where its scope matches: `node` (hex
  prefix of the LOCAL node id — which store's disk), `peer` (hex prefix
  of the REMOTE node id — which link/target), `endpoint` (rpc path
  prefix), `hash_prefix` (block hash hex prefix). Empty scope fields
  match everything.
- **Budgeted + counted.** `count` caps how many times a fault fires;
  every firing increments the `fired` counter on the spec AND a
  `chaos_fault_fired{kind=...}` series in the metrics registry, so a
  test can assert injection actually happened (a chaos test that
  silently injects nothing proves nothing).

Fault kinds:

  net_delay       sleep `delay_s` before a frame is sent
  net_drop        silently discard the frame (send- or recv-side)
  net_disconnect  kill the connection (ConnectionError out of the seam)
  net_slow        bandwidth drip: sleep nbytes / `rate_bps` per frame
  disk_read_error raise OSError(EIO) out of a local block/shard read
  disk_write_error raise OSError(EIO) out of a local block/shard write
  disk_torn_write persist only the first half of the written bytes
  disk_bitrot     flip one bit of the bytes read from the store
  rpc_error       raise RpcError instead of issuing the call
  rpc_hang        the call never completes: sleep out the caller's full
                  timeout, then raise asyncio.TimeoutError (exactly the
                  caller-visible shape of a hung peer)
  partition_zone  sever every CROSS-zone link of the zone named by the
                  `zone` scope field (ISSUE 16): a frame whose two
                  endpoints straddle the zone boundary dies with
                  ConnectionError in both directions, while intra-zone
                  links — inside AND outside the named zone — stay up.
                  Zone membership comes from the controller's
                  `zone_resolver` (installed from the layout by the
                  composition root; nodes it can't resolve are never
                  matched). This is the whole-failure-domain drill the
                  single-link net_disconnect can't express.

The controller is process-global (`arm()`/`disarm()`); a live node also
exposes it through admin `GET/POST /v1/chaos` and the `[chaos]` config
section arms it at boot.
"""

from __future__ import annotations

import asyncio
import errno
import random
import threading
from dataclasses import dataclass, field
from typing import Optional

from ..utils.metrics import registry

NET_KINDS = ("net_delay", "net_drop", "net_disconnect", "net_slow")
ZONE_KINDS = ("partition_zone",)
DISK_READ_KINDS = ("disk_read_error", "disk_bitrot")
DISK_WRITE_KINDS = ("disk_write_error", "disk_torn_write")
RPC_KINDS = ("rpc_error", "rpc_hang")
ALL_KINDS = (NET_KINDS + ZONE_KINDS + DISK_READ_KINDS
             + DISK_WRITE_KINDS + RPC_KINDS)

_HANG_FALLBACK = 3600.0  # a hang with no caller timeout still ends


class ChaosError(OSError):
    """Injected disk error; distinct type so logs name the injection."""

    def __init__(self, what: str):
        super().__init__(errno.EIO, f"chaos: injected {what}")


@dataclass
class FaultSpec:
    """One armed fault. Scope fields are hex/path prefixes; empty
    matches everything."""

    kind: str
    prob: float = 1.0
    count: Optional[int] = None  # firing budget; None = unlimited
    node: str = ""        # local node id hex prefix (disk faults)
    peer: str = ""        # remote node id hex prefix (net/rpc faults)
    endpoint: str = ""    # rpc endpoint path prefix
    hash_prefix: str = ""  # block hash hex prefix (disk faults)
    zone: str = ""        # partitioned zone name (partition_zone)
    delay_s: float = 0.05
    rate_bps: float = 1 << 20
    id: int = 0
    fired: int = 0

    def exhausted(self) -> bool:
        return self.count is not None and self.fired >= self.count

    def to_dict(self) -> dict:
        return {
            "id": self.id, "kind": self.kind, "prob": self.prob,
            "count": self.count, "node": self.node, "peer": self.peer,
            "endpoint": self.endpoint, "hash_prefix": self.hash_prefix,
            "zone": self.zone,
            "delay_s": self.delay_s, "rate_bps": self.rate_bps,
            "fired": self.fired, "exhausted": self.exhausted(),
        }


class ChaosController:
    """Holds the armed fault set and evaluates seam crossings."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.faults: list[FaultSpec] = []
        # node_id -> zone name (or None): installed by the composition
        # root / test harness from the layout (zones/health.py
        # layout_zone_resolver) so partition_zone faults can tell which
        # side of a frame sits in the named zone. Resolution only runs
        # while a partition_zone fault is armed.
        self.zone_resolver = None
        self._next_id = 1
        # seam-crossing evaluation happens on the event loop; arming
        # can come from admin handlers on the same loop or from test
        # threads — guard list mutation only
        self._lock = threading.Lock()
        self.total_fired = 0

    # ---- management ----------------------------------------------------

    def add(self, spec: FaultSpec) -> FaultSpec:
        if spec.kind not in ALL_KINDS:
            raise ValueError(f"unknown fault kind {spec.kind!r} "
                             f"(kinds: {', '.join(ALL_KINDS)})")
        if not 0.0 <= spec.prob <= 1.0:
            raise ValueError("prob must be in [0, 1]")
        with self._lock:
            spec.id = self._next_id
            self._next_id += 1
            self.faults.append(spec)
        return spec

    def remove(self, fault_id: int) -> bool:
        with self._lock:
            n = len(self.faults)
            self.faults = [f for f in self.faults if f.id != fault_id]
            return len(self.faults) != n

    def clear(self) -> None:
        with self._lock:
            self.faults = []

    def reseed(self, seed: int) -> None:
        """Fresh seed = fresh experiment: the rng AND the fired
        counters restart so runs are comparable."""
        self.seed = seed
        self.rng = random.Random(seed)
        self.total_fired = 0
        for f in self.faults:
            f.fired = 0

    def state(self) -> dict:
        return {
            "enabled": ACTIVE is self,
            "seed": self.seed,
            "total_fired": self.total_fired,
            "faults": [f.to_dict() for f in self.faults],
        }

    # ---- matching ------------------------------------------------------

    def _fire(self, kinds, node: bytes = b"", peer: bytes = b"",
              endpoint: str = "", hash32: bytes = b"",
              zone_pair=None) -> Optional[FaultSpec]:
        """First armed, in-scope, in-budget fault of one of `kinds`
        whose probability draw passes — with its fired counter already
        advanced. Runs under the lock: disk seams cross from
        asyncio.to_thread worker threads while net/rpc seams run on
        the event loop, and both the count budget and the seeded draw
        order must survive that.

        `zone_pair` is (local_zone, peer_zone) as resolved by the net
        seam — a partition_zone fault matches only when both sides
        resolved and EXACTLY ONE of them sits in the named zone (the
        cross-zone links of that zone; intra-zone traffic anywhere
        stays untouched)."""
        node_hex = node.hex() if node else ""
        peer_hex = peer.hex() if peer else ""
        hash_hex = hash32.hex() if hash32 else ""
        with self._lock:
            for f in self.faults:
                if f.kind not in kinds or f.exhausted():
                    continue
                if f.kind in ZONE_KINDS:
                    if not f.zone or zone_pair is None:
                        continue
                    lz, pz = zone_pair
                    if lz is None or pz is None:
                        continue
                    if (lz == f.zone) == (pz == f.zone):
                        continue
                if f.node and not node_hex.startswith(f.node):
                    continue
                if f.peer and not peer_hex.startswith(f.peer):
                    continue
                if f.endpoint and not endpoint.startswith(f.endpoint):
                    continue
                if f.hash_prefix \
                        and not hash_hex.startswith(f.hash_prefix):
                    continue
                if f.prob < 1.0 and self.rng.random() >= f.prob:
                    continue
                f.fired += 1
                self.total_fired += 1
                registry().inc("chaos_fault_fired", kind=f.kind)
                if f.exhausted():
                    _maybe_deactivate()
                return f
        return None

    # ---- seams ---------------------------------------------------------

    async def net_frame(self, direction: str, local: bytes, peer: bytes,
                        nbytes: int) -> bool:
        """Net seam, called per frame from Conn send/recv. Returns False
        when the frame must be DROPPED; may sleep (delay/slow) or raise
        ConnectionError (disconnect / partition_zone)."""
        zone_pair = None
        if self.zone_resolver is not None and local and peer \
                and any(f.kind in ZONE_KINDS for f in self.faults):
            zone_pair = (self.zone_resolver(local),
                         self.zone_resolver(peer))
        f = self._fire(NET_KINDS + ZONE_KINDS, node=local, peer=peer,
                       zone_pair=zone_pair)
        if f is None:
            return True
        if f.kind == "net_delay":
            await asyncio.sleep(f.delay_s)
            return True
        if f.kind == "net_slow":
            await asyncio.sleep(nbytes / max(f.rate_bps, 1.0))
            return True
        if f.kind == "net_drop":
            return False
        if f.kind == "partition_zone":
            raise ConnectionError(
                f"chaos: zone {f.zone} partitioned ({direction})")
        raise ConnectionError(
            f"chaos: injected disconnect ({direction})")

    async def rpc_call(self, endpoint: str, node: bytes,
                       timeout: Optional[float]) -> None:
        """RPC seam, called before a call is issued. May raise RpcError
        (rpc_error) or consume the caller's whole timeout and raise
        asyncio.TimeoutError (rpc_hang — the caller-visible shape of a
        peer that accepted the request and went silent)."""
        f = self._fire(RPC_KINDS, peer=node, endpoint=endpoint)
        if f is None:
            return
        if f.kind == "rpc_error":
            from ..utils.error import RpcError

            raise RpcError(f"chaos: injected rpc error on {endpoint}")
        await asyncio.sleep(timeout if timeout else _HANG_FALLBACK)
        raise asyncio.TimeoutError(
            f"chaos: injected hang on {endpoint} "
            f"(consumed {timeout}s timeout)")

    def disk_read(self, node: bytes, hash32: bytes, raw: bytes) -> bytes:
        """Disk read seam: raw bytes as read from the store. May raise
        ChaosError (EIO) or return the bytes with one bit flipped —
        downstream checksum/content verification is expected to catch
        the rot, exactly as it must for real media decay."""
        f = self._fire(DISK_READ_KINDS, node=node, hash32=hash32)
        if f is None:
            return raw
        if f.kind == "disk_read_error":
            raise ChaosError("read error")
        if not raw:
            return raw
        with self._lock:  # seeded draw order vs concurrent seams
            pos = self.rng.randrange(len(raw))
            bit = 1 << self.rng.randrange(8)
        rotted = bytearray(raw)
        rotted[pos] ^= bit
        return bytes(rotted)

    def disk_write(self, node: bytes, hash32: bytes, content) -> bytes:
        """Disk write seam: bytes about to be persisted. May raise
        ChaosError (EIO) or return a torn (half-length) image."""
        f = self._fire(DISK_WRITE_KINDS, node=node, hash32=hash32)
        if f is None:
            return content
        if f.kind == "disk_write_error":
            raise ChaosError("write error")
        return bytes(memoryview(content)[: len(content) // 2])


# ---- process-global arming ----------------------------------------------

# The seams read this ONE attribute. None = chaos fully disabled.
ACTIVE: Optional[ChaosController] = None

_controller = ChaosController()


def controller() -> ChaosController:
    """The process-global controller (exists even while disarmed, so
    admin GET /v1/chaos can always report state)."""
    return _controller


def arm(seed: Optional[int] = None) -> ChaosController:
    """Enable the seams. Optionally reseed for a deterministic run."""
    global ACTIVE
    if seed is not None:
        _controller.reseed(seed)
    ACTIVE = _controller
    return _controller


def disarm(clear: bool = True) -> None:
    """Back to the no-op fast path; by default also drop armed faults."""
    global ACTIVE
    ACTIVE = None
    if clear:
        _controller.clear()


def _maybe_deactivate() -> None:
    """When every armed fault has exhausted its budget, drop back to
    the no-op fast path automatically — a finished chaos experiment
    must not keep taxing the hot paths."""
    global ACTIVE
    if ACTIVE is not None and ACTIVE.faults \
            and all(f.exhausted() for f in ACTIVE.faults):
        ACTIVE = None
