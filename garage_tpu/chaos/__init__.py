"""Deterministic fault injection (see injector.py for the design).

Usage from tests / the admin API:

    from garage_tpu.chaos import arm, disarm, FaultSpec

    c = arm(seed=42)
    c.add(FaultSpec(kind="rpc_hang", peer=victim.hex()[:8],
                    endpoint="garage_tpu/block", count=10))
    try:
        ...drive the workload...
    finally:
        disarm()
"""

from .injector import (  # noqa: F401
    ALL_KINDS,
    ChaosController,
    ChaosError,
    FaultSpec,
    arm,
    controller,
    disarm,
)
