"""ByteStream: an async byte pipe with backpressure and error propagation.

Ref parity: src/net/stream.rs:29-213 (ByteStreamReader and friends).
Attached to requests/responses to stream block bodies without buffering
whole blocks in RAM.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Optional

from ..utils.background import spawn

_HIGH_WATER = 1 << 20  # pause producer above 1 MiB buffered


class StreamClosed(Exception):
    pass


class ByteStream:
    """Single-producer single-consumer byte pipe.

    Producer: push(bytes) / push_eof() / push_error(exc)  (sync, unbounded
    from remote; local producers use write() which honors backpressure).
    Consumer: read_chunk(n) -> b"" at EOF; async-iterable in chunks.
    """

    def __init__(self):
        self._chunks: list[bytes] = []
        self._size = 0
        self._eof = False
        self._error: Optional[Exception] = None
        self._data_ready = asyncio.Event()
        self._drained = asyncio.Event()
        self._drained.set()
        # consumer-progress callback (bytes drained); the transport wires
        # this to CREDIT grants for wire-level flow control
        self.on_consume: Optional[callable] = None

    # ---- producer ------------------------------------------------------

    def push(self, data: bytes) -> None:
        if self._eof or self._error:
            return
        if data:
            self._chunks.append(bytes(data))
            self._size += len(data)
            self._data_ready.set()
            if self._size >= _HIGH_WATER:
                self._drained.clear()

    def push_eof(self) -> None:
        self._eof = True
        self._data_ready.set()

    def push_error(self, exc: Exception) -> None:
        self._error = exc
        self._eof = True
        self._data_ready.set()

    async def write(self, data: bytes) -> None:
        """Backpressured push for local producers."""
        await self._drained.wait()
        if self._error:
            raise self._error
        if self._eof:
            raise StreamClosed("write after eof")
        # lint: ignore[GL12] single-producer contract; push() re-derives drained-ness from the LIVE buffer level, not from the pre-await read
        self.push(data)

    def close(self) -> None:
        self.push_eof()

    # ---- consumer ------------------------------------------------------

    async def read_chunk(self, max_len: int) -> bytes:
        while not self._chunks:
            if self._error:
                raise self._error
            if self._eof:
                return b""
            self._data_ready.clear()
            await self._data_ready.wait()
        head = self._chunks[0]
        if len(head) <= max_len:
            self._chunks.pop(0)
            out = head
        else:
            out = head[:max_len]
            self._chunks[0] = head[max_len:]
        self._size -= len(out)
        if self._size < _HIGH_WATER:
            self._drained.set()
        if self.on_consume is not None:
            self.on_consume(len(out))
        return out

    async def read_exact(self, n: int) -> bytes:
        parts: list[bytes] = []
        got = 0
        while got < n:
            chunk = await self.read_chunk(n - got)
            if not chunk:
                raise EOFError(f"stream ended at {got}/{n} bytes")
            parts.append(chunk)
            got += len(chunk)
        return b"".join(parts)

    async def read_all(self, limit: Optional[int] = None) -> bytes:
        parts: list[bytes] = []
        total = 0
        while True:
            chunk = await self.read_chunk(1 << 16)
            if not chunk:
                return b"".join(parts)
            total += len(chunk)
            if limit is not None and total > limit:
                raise ValueError(f"stream exceeds limit {limit}")
            parts.append(chunk)

    def discard(self) -> None:
        """Drop buffered data and swallow the rest."""
        self._chunks.clear()
        self._size = 0
        self._drained.set()
        if not self._eof:
            spawn(self._drain_rest(), "stream-discard-drain")

    async def _drain_rest(self) -> None:
        try:
            while await self.read_chunk(1 << 16):
                pass
        except Exception:
            pass  # lint: ignore[GL05] draining an abandoned stream; errors have no consumer

    def __aiter__(self) -> AsyncIterator[bytes]:
        return self._iter()

    async def _iter(self):
        while True:
            chunk = await self.read_chunk(1 << 16)
            if not chunk:
                return
            yield chunk

    @classmethod
    def from_bytes(cls, data: bytes) -> "ByteStream":
        s = cls()
        s.push(data)
        s.push_eof()
        return s

    @classmethod
    def from_iter(cls, it) -> "ByteStream":
        """Wrap an async iterator of bytes; pumped lazily by a task."""
        s = cls()

        async def pump():
            try:
                async for chunk in it:
                    await s.write(chunk)
                s.push_eof()
            except Exception as e:
                s.push_error(e)

        spawn(pump(), "stream-iter-pump")
        return s
