"""In-process loopback network for deterministic multi-node tests.

SURVEY.md §4 notes the reference can only test multi-node behavior by
forking real server processes; this transport runs any number of NetApp
nodes in one event loop with the exact same Conn framing/dispatch code,
minus TCP and crypto. Also powers the single-process dev cluster.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..utils.background import spawn
from ..utils.error import RpcError
from .conn import Conn


class LocalChannel:
    """Queue-backed duck-type of SecureChannel (send_frame/recv_frame).
    Frames pass through as (req_id, field, parts) tuples — zero copies,
    zero serialization — and max_chunk is effectively unbounded so a
    whole message is one queue item (there is no wire to preempt)."""

    max_chunk = 1 << 27

    def __init__(self, tx: asyncio.Queue, rx: asyncio.Queue):
        self.tx = tx
        self.rx = rx
        self._closed = False

    async def send_frame(self, req_id: int, field: int,
                         parts: list = ()) -> None:
        if self._closed:
            raise ConnectionError("channel closed")
        await self.tx.put((req_id, field, list(parts)))

    async def recv_frame(self):
        item = await self.rx.get()
        if item is None:
            raise ConnectionError("channel closed by peer")
        return item

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self.tx.put_nowait(None)
                self.rx.put_nowait(None)
            except Exception:
                pass  # lint: ignore[GL05] eof marker into a full/closed queue is a no-op


class LocalNetwork:
    """Registry of in-process nodes, addressable as ("local", n)."""

    def __init__(self):
        self.nodes: dict[bytes, "NetApp"] = {}  # noqa: F821
        self.addrs: dict[tuple, bytes] = {}
        self._n = 0
        self.partitions: set[frozenset] = set()  # failure injection

    def register(self, netapp) -> tuple[str, int]:
        addr = ("local", self._n)
        self._n += 1
        self.nodes[netapp.id] = netapp
        self.addrs[addr] = netapp.id
        netapp.local_net = self
        netapp.public_addr = addr
        netapp.bind_addr = addr
        return addr

    def partition(self, a: bytes, b: bytes) -> None:
        """Cut the link between two nodes (and drop live connections)."""
        self.partitions.add(frozenset((a, b)))
        for x, y in ((a, b), (b, a)):
            node = self.nodes.get(x)
            conn = node.conns.get(y) if node else None
            if conn is not None:
                spawn(conn.close(), "localnet-partition-close")

    def heal(self, a: bytes, b: bytes) -> None:
        self.partitions.discard(frozenset((a, b)))

    async def connect_from(self, src, addr, expected_id: Optional[bytes]) -> bytes:
        target_id = self.addrs.get(tuple(addr)) if addr is not None else expected_id
        if target_id is None:
            target_id = expected_id
        dst = self.nodes.get(target_id) if target_id else None
        if dst is None:
            raise RpcError(f"no local node at {addr}")
        if frozenset((src.id, dst.id)) in self.partitions:
            raise RpcError("network partition")
        if expected_id is not None and dst.id != expected_id:
            raise RpcError("peer identity mismatch")
        a, b = src.conns.get(dst.id), dst.conns.get(src.id)
        if a is not None and b is not None \
                and not a.closed.done() and not b.closed.done():
            return dst.id
        # one-sided remnant (e.g. a partition or a register-tiebreak
        # closed only one end): messages into it hang until timeout —
        # drop both ends before wiring a fresh pair
        for x, y in ((src, dst), (dst, src)):
            c = x.conns.get(y.id)
            if c is not None:
                await c.close()
        q_ab: asyncio.Queue = asyncio.Queue()
        q_ba: asyncio.Queue = asyncio.Queue()
        chan_a = LocalChannel(q_ab, q_ba)
        chan_b = LocalChannel(q_ba, q_ab)
        src._register(dst.id, chan_a, initiator=True)
        dst._register(src.id, chan_b, initiator=False)
        return dst.id
