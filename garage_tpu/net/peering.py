"""Full-mesh peering: pings, peer exchange, failure detection, reconnect.

Ref parity: src/net/peering.rs:23-615. Same state machine
(Ourself/Connected/Trying/Waiting/Abandonned), ping every 15 s carrying a
hash of the known peer list (pull the list on mismatch), failure
declared after 4 failed pings of 10 s each, reconnect with backoff.
Ping RTT stats feed the rpc layer's request ordering
(src/rpc/rpc_helper.rs:621-660).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ..utils.background import spawn
from ..utils.data import blake2sum
from ..utils.metrics import registry
from .message import PRIO_HIGH
from .netapp import NetApp

log = logging.getLogger("garage_tpu.net.peering")

PING_INTERVAL = 15.0
PING_TIMEOUT = 10.0
FAILED_PING_THRESHOLD = 4
CONN_RETRY_INTERVAL = 30.0
CONN_MAX_RETRIES = 10

# ---- per-peer RPC health (consumed by rpc/rpc_helper.py) ----------------
#
# Dean & Barroso, "The Tail at Scale" (CACM 2013): at scale the slow
# outliers dominate user-visible latency, and the fix is to stop
# treating every peer as equally healthy. This tracker is the shared
# observation point: every RpcHelper call records its outcome here
# (RpcHelper instances are per-subsystem, PeeringManager is per-node),
# and three consumers read it back —
#   * request_order deprioritizes peers whose circuit breaker is open,
#   * per-call timeouts derive from the peer's observed p99 instead of
#     the flat 30 s default,
#   * hedged reads fire a backup request after the peer's observed p95
#     instead of waiting for an error.

HEALTH_WINDOW = 128        # latency samples kept per peer (ring)
HEALTH_MIN_SAMPLES = 8     # below this, flat defaults stay in force
ERR_ALPHA = 0.2            # EWMA step for the error-rate estimate
BREAKER_FAILURES = 5       # consecutive failures that open the breaker
BREAKER_COOLDOWN = 5.0     # open -> half-open after this many seconds
BREAKER_HALF_OPEN_PROBES = 2  # in-flight probe budget while half-open
ADAPTIVE_MULT = 4.0        # adaptive timeout = clamp(p99 * this)
ADAPTIVE_MIN_S = 1.0       # never time out faster than this
HEDGE_DELAY_MIN = 0.01
HEDGE_DELAY_MAX = 5.0
HEDGE_DELAY_DEFAULT = 0.25  # hedge delay before any samples exist
HEDGE_BUCKET_CAP = 16.0    # burst budget of the global hedge limiter


class PeerHealth:
    """One peer's health: EWMA error rate + a fixed-size latency ring
    (order statistics over 128 floats are exact and cheap — a real
    quantile sketch buys nothing at this window size) + breaker state."""

    __slots__ = ("err_ewma", "lat", "_idx", "samples", "consec_failures",
                 "breaker", "opened_at", "probes_in_flight")

    def __init__(self):
        self.err_ewma = 0.0
        self.lat: list[float] = []
        self._idx = 0
        self.samples = 0
        self.consec_failures = 0
        self.breaker = "closed"  # closed | open | half_open
        self.opened_at = 0.0
        self.probes_in_flight = 0

    def observe_latency(self, dt: float) -> None:
        if len(self.lat) < HEALTH_WINDOW:
            self.lat.append(dt)
        else:
            self.lat[self._idx] = dt
            self._idx = (self._idx + 1) % HEALTH_WINDOW
        self.samples += 1

    def quantile(self, q: float) -> Optional[float]:
        if not self.lat:
            return None
        s = sorted(self.lat)
        return s[min(len(s) - 1, int(q * len(s)))]


class PeerHealthTracker:
    """Cluster-wide health map + the three-state circuit breaker and
    the global hedge budget. All methods are event-loop-synchronous."""

    def __init__(self):
        self.peers: dict[bytes, PeerHealth] = {}
        self.hedging_enabled = True
        # backup pushes for IDEMPOTENT writes (erasure shard puts are
        # content-addressed, so a duplicate landing is a no-op); the
        # `[rpc] hedge_writes` knob — writes additionally need an
        # explicit per-call hedge=True opt-in, audited by GL02
        self.write_hedging_enabled = True
        self.adaptive_timeout_enabled = True
        self.hedge_rate = 8.0  # sustained hedges/s across all calls
        self._hedge_tokens = HEDGE_BUCKET_CAP
        self._hedge_t = time.monotonic()
        self.hedges_launched = 0
        self.hedge_wins = 0
        self.breaker_opens = 0
        self.breaker_closes = 0

    def configure(self, hedging: Optional[bool] = None,
                  hedge_rate: Optional[float] = None,
                  adaptive_timeout: Optional[bool] = None,
                  write_hedging: Optional[bool] = None) -> None:
        if hedging is not None:
            self.hedging_enabled = bool(hedging)
        if hedge_rate is not None:
            self.hedge_rate = max(0.0, float(hedge_rate))
        if adaptive_timeout is not None:
            self.adaptive_timeout_enabled = bool(adaptive_timeout)
        if write_hedging is not None:
            self.write_hedging_enabled = bool(write_hedging)

    def reset(self) -> None:
        """Drop all observations (bench A/B legs must not inherit the
        previous leg's breakers and quantiles)."""
        self.peers.clear()
        self._hedge_tokens = HEDGE_BUCKET_CAP
        self._hedge_t = time.monotonic()

    def _peer(self, node: bytes) -> PeerHealth:
        p = self.peers.get(node)
        if p is None:
            p = self.peers[node] = PeerHealth()
        return p

    # ---- outcome recording --------------------------------------------

    def record_success(self, node: bytes,
                       latency: Optional[float] = None) -> None:
        p = self._peer(node)
        p.err_ewma *= 1.0 - ERR_ALPHA
        p.consec_failures = 0
        if p.probes_in_flight > 0:
            p.probes_in_flight -= 1
        if latency is not None:
            p.observe_latency(latency)
        if p.breaker != "closed":
            p.breaker = "closed"
            p.probes_in_flight = 0
            self.breaker_closes += 1
            registry().inc("rpc_breaker_transition", to="closed")

    def record_failure(self, node: bytes,
                       latency: Optional[float] = None) -> None:
        p = self._peer(node)
        p.err_ewma = (1.0 - ERR_ALPHA) * p.err_ewma + ERR_ALPHA
        p.consec_failures += 1
        if p.probes_in_flight > 0:
            p.probes_in_flight -= 1
        if latency is not None:
            # timed-out calls land here with their full elapsed time:
            # failures must push the observed tail UP so the adaptive
            # timeout backs off instead of spiraling tighter
            p.observe_latency(latency)
        if p.breaker == "half_open" or (
                p.breaker == "closed"
                and p.consec_failures >= BREAKER_FAILURES):
            p.breaker = "open"
            p.opened_at = time.monotonic()
            p.probes_in_flight = 0
            self.breaker_opens += 1
            registry().inc("rpc_breaker_transition", to="open")

    def record_ping_ok(self, node: bytes) -> None:
        """A successful ping: no latency sample (ping RTTs are not
        data-RPC latencies), but it clears the consecutive-failure
        count and closes a half-open breaker — on an idle cluster no
        data call will ever come along to probe a recovered peer, and
        it must not sit deprioritized forever. A peer that answers
        pings but hangs data RPCs re-opens after the next failures."""
        p = self.peers.get(node)
        if p is None:
            return
        p.consec_failures = 0
        if self.breaker_state(node) == "half_open":
            p.breaker = "closed"
            p.probes_in_flight = 0
            self.breaker_closes += 1
            registry().inc("rpc_breaker_transition", to="closed")

    # ---- breaker reads -------------------------------------------------

    def breaker_state(self, node: bytes,
                      now: Optional[float] = None) -> str:
        p = self.peers.get(node)
        if p is None:
            return "closed"
        if p.breaker == "open":
            if (now if now is not None else time.monotonic()) \
                    - p.opened_at >= BREAKER_COOLDOWN:
                p.breaker = "half_open"
                p.probes_in_flight = 0
                registry().inc("rpc_breaker_transition", to="half_open")
        return p.breaker

    def breaker_rank(self, node: bytes,
                     now: Optional[float] = None) -> int:
        """Ordering penalty for request_order: 0 closed, 1 half-open
        with probe budget left, 2 half-open exhausted, 3 open."""
        st = self.breaker_state(node, now)
        if st == "closed":
            return 0
        if st == "half_open":
            p = self.peers[node]
            return 1 if p.probes_in_flight < BREAKER_HALF_OPEN_PROBES \
                else 2
        return 3

    def note_launch(self, node: bytes) -> None:
        """Count a call launched at a half-open peer against its probe
        budget (budget-exhausted peers rank behind healthy ones)."""
        p = self.peers.get(node)
        if p is not None and p.breaker == "half_open":
            p.probes_in_flight += 1

    # ---- derived knobs -------------------------------------------------

    def call_timeout(self, node: bytes,
                     flat: Optional[float]) -> Optional[float]:
        """Adaptive per-call timeout: clamp(p99 * 4) once the peer has
        enough samples; the caller's flat value is both the default and
        the ceiling (adaptation only ever tightens)."""
        if flat is None or not self.adaptive_timeout_enabled:
            return flat
        p = self.peers.get(node)
        if p is None or p.samples < HEALTH_MIN_SAMPLES:
            return flat
        q = p.quantile(0.99)
        if q is None:
            return flat
        return min(flat, max(ADAPTIVE_MIN_S, q * ADAPTIVE_MULT))

    def hedge_delay(self, nodes) -> float:
        """How long to wait on the in-flight request(s) before launching
        a backup: the worst observed p95 among them, lightly padded."""
        worst = None
        for n in nodes:
            p = self.peers.get(n)
            if p is None or p.samples < HEALTH_MIN_SAMPLES:
                continue
            q = p.quantile(0.95)
            if q is not None and (worst is None or q > worst):
                worst = q
        if worst is None:
            return HEDGE_DELAY_DEFAULT
        return min(HEDGE_DELAY_MAX, max(HEDGE_DELAY_MIN, worst * 1.5))

    def try_take_hedge(self) -> bool:
        """Global hedge-rate cap (token bucket): hedging bounds tail
        latency at a few percent extra load, but only if something
        bounds the hedges themselves."""
        now = time.monotonic()
        self._hedge_tokens = min(
            HEDGE_BUCKET_CAP,
            self._hedge_tokens + (now - self._hedge_t) * self.hedge_rate)
        self._hedge_t = now
        if self._hedge_tokens >= 1.0:
            self._hedge_tokens -= 1.0
            self.hedges_launched += 1
            return True
        return False

    def record_hedge_win(self) -> None:
        self.hedge_wins += 1

    # ---- observability -------------------------------------------------

    def stats(self) -> dict:
        return {
            "hedges_launched": self.hedges_launched,
            "hedge_wins": self.hedge_wins,
            "breaker_opens": self.breaker_opens,
            "breaker_closes": self.breaker_closes,
            "hedging_enabled": self.hedging_enabled,
            "write_hedging_enabled": self.write_hedging_enabled,
            "adaptive_timeout_enabled": self.adaptive_timeout_enabled,
        }

    def peer_state(self) -> dict:
        out = {}
        for node, p in self.peers.items():
            out[node.hex()[:16]] = {
                "breaker": self.breaker_state(node),
                "error_rate": round(p.err_ewma, 4),
                "samples": p.samples,
                "p50_s": p.quantile(0.50),
                "p95_s": p.quantile(0.95),
                "p99_s": p.quantile(0.99),
            }
        return out


class PeerConnState(Enum):
    OURSELF = "ourself"
    CONNECTED = "connected"
    TRYING = "trying"
    WAITING = "waiting"
    ABANDONNED = "abandonned"


@dataclass
class PeerInfo:
    id: bytes
    addr: Optional[tuple]
    state: PeerConnState
    last_seen: Optional[float] = None
    ping_avg: Optional[float] = None
    ping_max: Optional[float] = None


@dataclass
class _Peer:
    id: bytes
    addr: Optional[tuple] = None
    state: PeerConnState = PeerConnState.WAITING
    next_retry: float = 0.0
    retries: int = 0
    failed_pings: int = 0
    last_seen: Optional[float] = None
    pings: list = field(default_factory=list)  # last RTTs

    def record_ping(self, rtt: float) -> None:
        self.pings.append(rtt)
        if len(self.pings) > 10:
            self.pings.pop(0)
        self.last_seen = time.monotonic()
        self.failed_pings = 0


class PeeringManager:
    """Keeps this node connected to every known peer."""

    def __init__(
        self,
        netapp: NetApp,
        bootstrap: list,
        ping_interval: float = PING_INTERVAL,
        ping_timeout: float = PING_TIMEOUT,
        retry_interval: float = CONN_RETRY_INTERVAL,
    ):
        self.netapp = netapp
        self.ping_interval = ping_interval
        self.ping_timeout = ping_timeout
        self.retry_interval = retry_interval
        # hot-hash hint piggyback (ISSUE 15, block/cache_tier.py): the
        # cluster cache tier registers a provider (this node's hottest
        # cache keys) and a sink (a peer's hints). The peering layer
        # stays block-agnostic — hints are opaque byte strings riding
        # the pings it already sends, in BOTH directions (request and
        # reply), so a hint set converges in ~one ping interval.
        self.hint_provider = None  # () -> list[bytes]
        self.hint_sink = None      # (from_node: bytes, hints) -> None
        # shared per-peer rpc health (breakers, latency quantiles);
        # PeeringManager is the one per-node object every RpcHelper
        # can reach through system.peering
        self.health = PeerHealthTracker()
        self.peers: dict[bytes, _Peer] = {
            netapp.id: _Peer(netapp.id, netapp.public_addr, PeerConnState.OURSELF)
        }
        # bootstrap addresses whose node id we don't know yet; moved into
        # self.peers once a connection reveals the id (kept separate — an
        # in-band key prefix would collide with real 32-byte ids)
        self.pending: dict[tuple, _Peer] = {}
        for entry in bootstrap:
            addr, pid = (entry, None) if not _is_pair(entry) else entry
            self.add_peer(tuple(addr) if addr else None, pid)

        self.ep_ping = netapp.endpoint("garage_net/peering:ping").set_handler(self._h_ping)
        self.ep_list = netapp.endpoint("garage_net/peering:list").set_handler(self._h_list)
        self.ep_hello = netapp.endpoint("garage_net/peering:hello").set_handler(self._h_hello)
        netapp.on_connected.append(self._on_connected)
        netapp.on_disconnected.append(self._on_disconnected)
        self._stop = asyncio.Event()

    # ---- public --------------------------------------------------------

    def get_peer_list(self) -> list[PeerInfo]:
        out = []
        for p in self.peers.values():
            if p.addr is None and p.id != self.netapp.id:
                # inbound connection that never announced a public addr:
                # a transient RPC client (operator CLI), not a cluster
                # member — keep it out of membership, gossip and metrics
                # (ref: only Hello-announcing nodes enter the peer list,
                # src/net/netapp.rs:440-470)
                continue
            avg = sum(p.pings) / len(p.pings) if p.pings else None
            mx = max(p.pings) if p.pings else None
            out.append(PeerInfo(p.id, p.addr, p.state, p.last_seen, avg, mx))
        return out

    def ping_avg(self, node: bytes) -> Optional[float]:
        p = self.peers.get(node)
        return (sum(p.pings) / len(p.pings)) if p and p.pings else None

    def add_peer(self, addr, pid: Optional[bytes] = None) -> None:
        if pid == self.netapp.id:
            return
        if pid is None:
            if addr is not None and addr not in self.pending:
                self.pending[addr] = _Peer(None, addr)
            return
        if pid in self.peers:
            if addr is not None:
                self.peers[pid].addr = addr
        else:
            self.peers[pid] = _Peer(pid, addr)
        if addr is not None:
            self.pending.pop(addr, None)

    async def stop(self) -> None:
        self._stop.set()

    # ---- loops ---------------------------------------------------------

    async def run(self) -> None:
        ping_task = asyncio.create_task(self._ping_loop())
        conn_task = asyncio.create_task(self._connect_loop())
        # supervised (cancelled below): not leaks for the sanitizer
        ping_task._garage_background = True
        conn_task._garage_background = True
        await self._stop.wait()
        ping_task.cancel()
        conn_task.cancel()

    async def _ping_loop(self) -> None:
        while True:
            await asyncio.sleep(self.ping_interval * random.uniform(0.8, 1.2))
            self.netapp._ordered.prune()
            targets = [
                p for p in self.peers.values() if p.state == PeerConnState.CONNECTED
            ]
            await asyncio.gather(*(self._ping_one(p) for p in targets))

    def _hot_hints(self) -> list:
        if self.hint_provider is None:
            return []
        try:
            return list(self.hint_provider())
        except Exception as e:
            log.debug("hint provider failed: %s", e)
            return []

    def _take_hints(self, from_node: bytes, payload: dict) -> None:
        hot = payload.get("hot")
        if not hot or self.hint_sink is None:
            return
        try:
            self.hint_sink(from_node, hot)
        except Exception as e:
            log.debug("hint sink failed: %s", e)

    async def _ping_one(self, peer: _Peer) -> None:
        t0 = time.monotonic()
        try:
            payload = {"hash": self._peer_list_hash()}
            hot = self._hot_hints()
            if hot:
                payload["hot"] = hot
            resp, _ = await self.ep_ping.call(
                peer.id, payload, PRIO_HIGH, timeout=self.ping_timeout
            )
            peer.record_ping(time.monotonic() - t0)
            self.health.record_ping_ok(peer.id)
            self._take_hints(peer.id, resp)
            if resp.get("hash") != self._peer_list_hash():
                await self._pull_peer_list(peer.id)
        except Exception:
            peer.failed_pings += 1
            # a failed ping is a health failure too (no latency sample:
            # ping RTTs are not data-RPC latencies) — enough failed
            # pings open the breaker even with no data traffic flowing
            self.health.record_failure(peer.id)
            if peer.failed_pings >= FAILED_PING_THRESHOLD:
                log.info("peer %s failed %d pings, disconnecting", peer.id[:4].hex(), peer.failed_pings)
                conn = self.netapp.conns.get(peer.id)
                if conn is not None:
                    await conn.close()

    async def _connect_loop(self) -> None:
        while True:
            now = time.monotonic()
            for peer in list(self.peers.values()) + list(self.pending.values()):
                if (
                    peer.state == PeerConnState.WAITING
                    and peer.next_retry <= now
                    and peer.addr is not None
                ):
                    peer.state = PeerConnState.TRYING
                    spawn(self._try_connect(peer), "peer-connect")
            await asyncio.sleep(min(1.0, self.retry_interval / 10))

    async def _try_connect(self, peer: _Peer) -> None:
        try:
            got = await self.netapp.try_connect(peer.addr, peer.id)
            if peer.id is None:
                # learned the real id for a bootstrap addr
                self.pending.pop(peer.addr, None)
                self.add_peer(peer.addr, got)
                p2 = self.peers.get(got)
                if p2 is not None:
                    p2.state = PeerConnState.CONNECTED
        except Exception as e:
            log.debug("connect to %s failed: %s", peer.addr, e)
            peer.retries += 1
            if peer.retries >= CONN_MAX_RETRIES:
                peer.state = PeerConnState.ABANDONNED
            else:
                peer.state = PeerConnState.WAITING
                backoff = self.retry_interval * min(2 ** (peer.retries - 1), 8)
                peer.next_retry = time.monotonic() + backoff * random.uniform(0.8, 1.2)

    # ---- netapp callbacks ---------------------------------------------

    def _on_connected(self, peer_id: bytes, incoming: bool) -> None:
        p = self.peers.get(peer_id)
        if p is None:
            p = self.peers[peer_id] = _Peer(peer_id)
        p.state = PeerConnState.CONNECTED
        p.retries = 0
        p.failed_pings = 0
        p.last_seen = time.monotonic()
        if not incoming:
            # tell the acceptor our public address (ref Hello message,
            # src/net/netapp.rs:440-470)
            spawn(self._send_hello(peer_id), "peer-hello")

    async def _send_hello(self, peer_id: bytes) -> None:
        try:
            await self.ep_hello.call(
                peer_id, {"addr": list(self.netapp.public_addr or ())}, PRIO_HIGH, timeout=10.0
            )
        except Exception as e:
            log.debug("hello to %s failed: %s", peer_id[:4].hex(), e)

    def _on_disconnected(self, peer_id: bytes) -> None:
        p = self.peers.get(peer_id)
        if p is None:
            return
        if p.addr is None:
            # transient client gone: forget it, nothing to reconnect to
            del self.peers[peer_id]
            return
        if p.state == PeerConnState.CONNECTED:
            p.state = PeerConnState.WAITING
            p.next_retry = time.monotonic() + self.retry_interval * random.uniform(0.5, 1.0)

    # ---- rpc handlers --------------------------------------------------

    def _peer_list_hash(self) -> bytes:
        # covers exactly what _h_list serves (id+addr known), so hash
        # equality <=> list equality and pings don't re-pull forever
        items = sorted(
            (p.id, tuple(p.addr))
            for p in self.peers.values()
            if p.addr is not None
        )
        return blake2sum(repr(items).encode())

    async def _h_ping(self, from_node, payload, stream):
        p = self.peers.get(from_node)
        if p is not None:
            p.last_seen = time.monotonic()
        self._take_hints(from_node, payload)
        out = {"hash": self._peer_list_hash()}
        hot = self._hot_hints()
        if hot:
            out["hot"] = hot
        return out

    async def _h_list(self, from_node, payload, stream):
        return {
            "peers": [
                [p.id, list(p.addr)]
                for p in self.peers.values()
                if p.addr is not None
            ]
        }

    async def _h_hello(self, from_node, payload, stream):
        addr = payload.get("addr")
        if addr:
            self.add_peer(tuple(addr), from_node)
            p = self.peers.get(from_node)
            if p is not None:
                p.addr = tuple(addr)
        return {}

    async def _pull_peer_list(self, node: bytes) -> None:
        try:
            resp, _ = await self.ep_list.call(node, {}, PRIO_HIGH, timeout=self.ping_timeout)
            for pid, addr in resp.get("peers", []):
                self.add_peer(tuple(addr) if addr else None, bytes(pid))
        except Exception as e:
            log.debug("peer-list pull from %s failed: %s",
                      node[:4].hex(), e)


def _is_pair(entry) -> bool:
    return (
        isinstance(entry, (tuple, list))
        and len(entry) == 2
        and (entry[1] is None or isinstance(entry[1], bytes))
        and isinstance(entry[0], (tuple, list))
    )
