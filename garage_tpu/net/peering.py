"""Full-mesh peering: pings, peer exchange, failure detection, reconnect.

Ref parity: src/net/peering.rs:23-615. Same state machine
(Ourself/Connected/Trying/Waiting/Abandonned), ping every 15 s carrying a
hash of the known peer list (pull the list on mismatch), failure
declared after 4 failed pings of 10 s each, reconnect with backoff.
Ping RTT stats feed the rpc layer's request ordering
(src/rpc/rpc_helper.rs:621-660).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ..utils.data import blake2sum
from .message import PRIO_HIGH
from .netapp import NetApp

log = logging.getLogger("garage_tpu.net.peering")

PING_INTERVAL = 15.0
PING_TIMEOUT = 10.0
FAILED_PING_THRESHOLD = 4
CONN_RETRY_INTERVAL = 30.0
CONN_MAX_RETRIES = 10


class PeerConnState(Enum):
    OURSELF = "ourself"
    CONNECTED = "connected"
    TRYING = "trying"
    WAITING = "waiting"
    ABANDONNED = "abandonned"


@dataclass
class PeerInfo:
    id: bytes
    addr: Optional[tuple]
    state: PeerConnState
    last_seen: Optional[float] = None
    ping_avg: Optional[float] = None
    ping_max: Optional[float] = None


@dataclass
class _Peer:
    id: bytes
    addr: Optional[tuple] = None
    state: PeerConnState = PeerConnState.WAITING
    next_retry: float = 0.0
    retries: int = 0
    failed_pings: int = 0
    last_seen: Optional[float] = None
    pings: list = field(default_factory=list)  # last RTTs

    def record_ping(self, rtt: float) -> None:
        self.pings.append(rtt)
        if len(self.pings) > 10:
            self.pings.pop(0)
        self.last_seen = time.monotonic()
        self.failed_pings = 0


class PeeringManager:
    """Keeps this node connected to every known peer."""

    def __init__(
        self,
        netapp: NetApp,
        bootstrap: list,
        ping_interval: float = PING_INTERVAL,
        ping_timeout: float = PING_TIMEOUT,
        retry_interval: float = CONN_RETRY_INTERVAL,
    ):
        self.netapp = netapp
        self.ping_interval = ping_interval
        self.ping_timeout = ping_timeout
        self.retry_interval = retry_interval
        self.peers: dict[bytes, _Peer] = {
            netapp.id: _Peer(netapp.id, netapp.public_addr, PeerConnState.OURSELF)
        }
        # bootstrap addresses whose node id we don't know yet; moved into
        # self.peers once a connection reveals the id (kept separate — an
        # in-band key prefix would collide with real 32-byte ids)
        self.pending: dict[tuple, _Peer] = {}
        for entry in bootstrap:
            addr, pid = (entry, None) if not _is_pair(entry) else entry
            self.add_peer(tuple(addr) if addr else None, pid)

        self.ep_ping = netapp.endpoint("garage_net/peering:ping").set_handler(self._h_ping)
        self.ep_list = netapp.endpoint("garage_net/peering:list").set_handler(self._h_list)
        self.ep_hello = netapp.endpoint("garage_net/peering:hello").set_handler(self._h_hello)
        netapp.on_connected.append(self._on_connected)
        netapp.on_disconnected.append(self._on_disconnected)
        self._stop = asyncio.Event()

    # ---- public --------------------------------------------------------

    def get_peer_list(self) -> list[PeerInfo]:
        out = []
        for p in self.peers.values():
            if p.addr is None and p.id != self.netapp.id:
                # inbound connection that never announced a public addr:
                # a transient RPC client (operator CLI), not a cluster
                # member — keep it out of membership, gossip and metrics
                # (ref: only Hello-announcing nodes enter the peer list,
                # src/net/netapp.rs:440-470)
                continue
            avg = sum(p.pings) / len(p.pings) if p.pings else None
            mx = max(p.pings) if p.pings else None
            out.append(PeerInfo(p.id, p.addr, p.state, p.last_seen, avg, mx))
        return out

    def ping_avg(self, node: bytes) -> Optional[float]:
        p = self.peers.get(node)
        return (sum(p.pings) / len(p.pings)) if p and p.pings else None

    def add_peer(self, addr, pid: Optional[bytes] = None) -> None:
        if pid == self.netapp.id:
            return
        if pid is None:
            if addr is not None and addr not in self.pending:
                self.pending[addr] = _Peer(None, addr)
            return
        if pid in self.peers:
            if addr is not None:
                self.peers[pid].addr = addr
        else:
            self.peers[pid] = _Peer(pid, addr)
        if addr is not None:
            self.pending.pop(addr, None)

    async def stop(self) -> None:
        self._stop.set()

    # ---- loops ---------------------------------------------------------

    async def run(self) -> None:
        ping_task = asyncio.create_task(self._ping_loop())
        conn_task = asyncio.create_task(self._connect_loop())
        await self._stop.wait()
        ping_task.cancel()
        conn_task.cancel()

    async def _ping_loop(self) -> None:
        while True:
            await asyncio.sleep(self.ping_interval * random.uniform(0.8, 1.2))
            self.netapp._ordered.prune()
            targets = [
                p for p in self.peers.values() if p.state == PeerConnState.CONNECTED
            ]
            await asyncio.gather(*(self._ping_one(p) for p in targets))

    async def _ping_one(self, peer: _Peer) -> None:
        t0 = time.monotonic()
        try:
            resp, _ = await self.ep_ping.call(
                peer.id, {"hash": self._peer_list_hash()}, PRIO_HIGH, timeout=self.ping_timeout
            )
            peer.record_ping(time.monotonic() - t0)
            if resp.get("hash") != self._peer_list_hash():
                await self._pull_peer_list(peer.id)
        except Exception:
            peer.failed_pings += 1
            if peer.failed_pings >= FAILED_PING_THRESHOLD:
                log.info("peer %s failed %d pings, disconnecting", peer.id[:4].hex(), peer.failed_pings)
                conn = self.netapp.conns.get(peer.id)
                if conn is not None:
                    await conn.close()

    async def _connect_loop(self) -> None:
        while True:
            now = time.monotonic()
            for peer in list(self.peers.values()) + list(self.pending.values()):
                if (
                    peer.state == PeerConnState.WAITING
                    and peer.next_retry <= now
                    and peer.addr is not None
                ):
                    peer.state = PeerConnState.TRYING
                    asyncio.ensure_future(self._try_connect(peer))
            await asyncio.sleep(min(1.0, self.retry_interval / 10))

    async def _try_connect(self, peer: _Peer) -> None:
        try:
            got = await self.netapp.try_connect(peer.addr, peer.id)
            if peer.id is None:
                # learned the real id for a bootstrap addr
                self.pending.pop(peer.addr, None)
                self.add_peer(peer.addr, got)
                p2 = self.peers.get(got)
                if p2 is not None:
                    p2.state = PeerConnState.CONNECTED
        except Exception as e:
            log.debug("connect to %s failed: %s", peer.addr, e)
            peer.retries += 1
            if peer.retries >= CONN_MAX_RETRIES:
                peer.state = PeerConnState.ABANDONNED
            else:
                peer.state = PeerConnState.WAITING
                backoff = self.retry_interval * min(2 ** (peer.retries - 1), 8)
                peer.next_retry = time.monotonic() + backoff * random.uniform(0.8, 1.2)

    # ---- netapp callbacks ---------------------------------------------

    def _on_connected(self, peer_id: bytes, incoming: bool) -> None:
        p = self.peers.get(peer_id)
        if p is None:
            p = self.peers[peer_id] = _Peer(peer_id)
        p.state = PeerConnState.CONNECTED
        p.retries = 0
        p.failed_pings = 0
        p.last_seen = time.monotonic()
        if not incoming:
            # tell the acceptor our public address (ref Hello message,
            # src/net/netapp.rs:440-470)
            asyncio.ensure_future(self._send_hello(peer_id))

    async def _send_hello(self, peer_id: bytes) -> None:
        try:
            await self.ep_hello.call(
                peer_id, {"addr": list(self.netapp.public_addr or ())}, PRIO_HIGH, timeout=10.0
            )
        except Exception:
            pass

    def _on_disconnected(self, peer_id: bytes) -> None:
        p = self.peers.get(peer_id)
        if p is None:
            return
        if p.addr is None:
            # transient client gone: forget it, nothing to reconnect to
            del self.peers[peer_id]
            return
        if p.state == PeerConnState.CONNECTED:
            p.state = PeerConnState.WAITING
            p.next_retry = time.monotonic() + self.retry_interval * random.uniform(0.5, 1.0)

    # ---- rpc handlers --------------------------------------------------

    def _peer_list_hash(self) -> bytes:
        # covers exactly what _h_list serves (id+addr known), so hash
        # equality <=> list equality and pings don't re-pull forever
        items = sorted(
            (p.id, tuple(p.addr))
            for p in self.peers.values()
            if p.addr is not None
        )
        return blake2sum(repr(items).encode())

    async def _h_ping(self, from_node, payload, stream):
        p = self.peers.get(from_node)
        if p is not None:
            p.last_seen = time.monotonic()
        return {"hash": self._peer_list_hash()}

    async def _h_list(self, from_node, payload, stream):
        return {
            "peers": [
                [p.id, list(p.addr)]
                for p in self.peers.values()
                if p.addr is not None
            ]
        }

    async def _h_hello(self, from_node, payload, stream):
        addr = payload.get("addr")
        if addr:
            self.add_peer(tuple(addr), from_node)
            p = self.peers.get(from_node)
            if p is not None:
                p.addr = tuple(addr)
        return {}

    async def _pull_peer_list(self, node: bytes) -> None:
        try:
            resp, _ = await self.ep_list.call(node, {}, PRIO_HIGH, timeout=self.ping_timeout)
            for pid, addr in resp.get("peers", []):
                self.add_peer(tuple(addr) if addr else None, bytes(pid))
        except Exception:
            pass


def _is_pair(entry) -> bool:
    return (
        isinstance(entry, (tuple, list))
        and len(entry) == 2
        and (entry[1] is None or isinstance(entry[1], bytes))
        and isinstance(entry[0], (tuple, list))
    )
