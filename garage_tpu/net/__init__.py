"""Transport mesh: authenticated, multiplexed, priority-scheduled RPC.

The reference's custom netapp stack (src/net/, SURVEY.md §2.3) rebuilt on
asyncio: typed endpoints, chunked framing with priorities + order tags +
cancellation, streamed bodies, full-mesh peering with failure detection.
Two interchangeable transports: real TCP (`netapp.NetApp.listen`) and an
in-process loopback network (`local.LocalNetwork`) for deterministic
multi-node tests — the improvement SURVEY.md §4 calls for over the
reference's forked-process-only test strategy.
"""

from .message import (  # noqa: F401
    PRIO_BACKGROUND,
    PRIO_HIGH,
    PRIO_NORMAL,
    PRIO_PRIMARY,
    PRIO_SECONDARY,
    OrderTag,
)
from .netapp import NetApp  # noqa: F401
from .endpoint import Endpoint  # noqa: F401
from .peering import PeeringManager, PeerConnState  # noqa: F401
from .local import LocalNetwork  # noqa: F401
