"""Authenticated channel + multiplexed, priority-scheduled framing.

Ref parity: src/net/{client,server,send,recv}.rs. Same wire concepts —
version-tag handshake gate, cluster-secret check, mutual public-key auth,
chunked frames `[u32 request_id][u16 flags|len][bytes]` with a max chunk
size, per-priority round-robin between in-flight streams, CANCEL frames —
rebuilt for asyncio, plus per-stream credit flow control (the reference
gets backpressure from its poll-driven scheduler; an asyncio push model
needs explicit credits or slow consumers buffer whole transfers).

Crypto: the reference uses the Secret-Handshake protocol + BoxStream
(kuska). Here: ed25519 identity keys sign a transcript that includes
X25519 ephemerals and an HMAC over the cluster `netid` (the shared
secret gate), then both directions run ChaCha20-Poly1305 with counter
nonces — same properties (mutual auth, cluster gate, confidentiality,
forward secrecy) with standard primitives from `cryptography`.

Frame flags (in the u32 len field):
  0x80000000 CONTINUES — more chunks follow for this section
  0x40000000 ERROR     — section is an error payload
  0x20000000 STREAM    — chunk belongs to the attached byte stream
  len = field & 0x0FFFFFFF, <= the channel's max_chunk
  field == 0xFFFFFFFF  — CANCEL marker for this request id
  field == 0xFFFFFFFE  — CREDIT grant; payload = u32 additional window

Body section layout (v3): [u32 hlen][msgpack header][raw blob bytes].
The header's last element is a blob key: when a request/reply payload
is a dict with one large bytes value (a block/shard), that value rides
OUTSIDE msgpack as the raw tail of the body and is re-attached on
receive. Together with scatter-gather frames (channels accept lists of
buffers; LocalChannel passes them through untouched) this removes ~5
full-payload copies per block RPC vs msgpack-embedding the bytes
(r4 profile: the copies were a top-3 cost on the PUT path).

Concurrency invariant: ALL outgoing records flow through _send_loop (the
single writer) — the AEAD nonce counter and frame ordering both depend
on it. CANCEL/CREDIT are enqueued control items, never written directly.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac as hmac_mod
import logging
import struct
from typing import Awaitable, Callable, Optional

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    HAVE_CRYPTO = True
except ModuleNotFoundError:
    # bare image: the in-process LocalChannel transport (net/local.py)
    # carries no crypto and keeps working; only the TCP SecureChannel
    # needs these wheels, and its handshakes refuse with a clear error
    HAVE_CRYPTO = False
    Ed25519PrivateKey = Ed25519PublicKey = None
    X25519PrivateKey = X25519PublicKey = ChaCha20Poly1305 = None

from ..chaos import injector as _chaos
from ..utils.error import RpcError
from .message import PRIO_HIGH, pack, unpack
from .stream import ByteStream

log = logging.getLogger("garage_tpu.net")

MAGIC = b"GRGTPU\x04\x00"  # protocol version gate (ref: net/netapp.rs:35-40)
# distinct magic for the no-crypto fallback wire: a crypto-equipped
# node and a bare one REJECT each other's hellos instead of silently
# downgrading the whole cluster to plaintext
PLAIN_MAGIC = b"GRGTPP\x04\x00"
# 256 KiB chunks on TCP: per-chunk costs (AEAD pass + header + writer
# wakeup) were the dominant CPU on the block path at the reference-style
# ~8 KiB (a 1.5 MiB shard transfer = ~190 chunks); at ~1 ms
# serialization per chunk the priority round-robin still keeps pings
# fresh. The in-process LocalChannel has no serialization cost, so it
# takes whole messages in one frame (local.py sets max_chunk huge).
MAX_CHUNK = 0x3FFF0
F_CONT = 0x80000000
F_ERROR = 0x40000000
F_STREAM = 0x20000000
LEN_MASK = 0x0FFFFFFF
CANCEL = 0xFFFFFFFF
CREDIT = 0xFFFFFFFE

# payload-dict values at least this big ride as a raw blob instead of
# being embedded in msgpack (saves a serialize + parse copy per side)
BLOB_MIN = 4096


def split_blob(payload):
    """-> (payload_without_blob, blob_key|None, blob|None). The largest
    qualifying bytes value of a dict payload is hoisted out of msgpack.
    Never mutates the caller's dict."""
    if type(payload) is dict:
        best_k, best = None, BLOB_MIN - 1
        for k, v in payload.items():
            if isinstance(v, (bytes, bytearray, memoryview)) \
                    and len(v) > best:
                best_k, best = k, len(v)
        if best_k is not None:
            rest = {k: v for k, v in payload.items() if k != best_k}
            return rest, best_k, payload[best_k]
    return payload, None, None


def _attach_blob(payload, blob_key, blob):
    """Re-attach a hoisted blob value into its dict payload."""
    if blob_key is not None and type(payload) is dict:
        payload[blob_key] = blob if blob is not None else b""
    return payload


def pack_body(header_obj, blob) -> list:
    """Body = [u32 hlen][msgpack header][blob] as a scatter list.
    u32: table-sync pushes batch whole entries into the header (e.g.
    sync.py Items with 64 x multi-KiB entries), which blows a u16 cap."""
    h = pack(header_obj)
    first = struct.pack("<I", len(h)) + h
    return [first, blob] if blob is not None else [first]


def parse_body(parts: list):
    """Inverse of pack_body over received buffers. Returns
    (header_obj, blob: bytes|None). parts arrive either exactly as sent
    (LocalChannel) or re-chunked (TCP); both shapes are handled."""
    first = parts[0]
    if len(first) >= 4:
        (hlen,) = struct.unpack_from("<I", first)
        if len(first) >= 4 + hlen:
            header = unpack(bytes(first[4:4 + hlen]))
            tail = first[4 + hlen:]
            blobs = ([tail] if len(tail) else []) + parts[1:]
            if not blobs:
                return header, None
            if len(blobs) == 1:
                b = blobs[0]
                return header, b if isinstance(b, bytes) else bytes(b)
            return header, b"".join(bytes(x) for x in blobs)
    # header split across frames (TCP re-chunking of a tiny first part)
    body = b"".join(bytes(p) for p in parts)
    (hlen,) = struct.unpack_from("<I", body)
    header = unpack(body[4:4 + hlen])
    blob = body[4 + hlen:] or None
    return header, blob

# Stream flow control: sender may have this many un-acked stream bytes in
# flight per request; receiver grants more as the consumer drains.
STREAM_WINDOW = 4 << 20
CREDIT_BATCH = 1 << 20  # grant credits in chunks this big


def _hmac(key: bytes, *parts: bytes) -> bytes:
    return hmac_mod.new(key, b"".join(parts), hashlib.blake2b).digest()[:32]


def _hkdf(secret: bytes, info: bytes) -> bytes:
    # compress info (label + both ephemerals, >64 B) into a full-width
    # key so the whole transcript context feeds key derivation
    ikey = hashlib.blake2b(info, digest_size=64).digest()
    return hashlib.blake2b(secret, key=ikey, digest_size=32).digest()


class HandshakeError(RpcError):
    pass


class PlainChannel:
    """No-crypto record layer for the `cryptography`-less fallback:
    [u32 len][u32 req_id][u32 field][payload]. Cluster membership is
    still gated (HMAC over netid in the plain handshake below) but
    there is NO confidentiality or per-record integrity — dev/test
    transport, never a production one."""

    max_chunk = MAX_CHUNK

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer

    async def send_frame(self, req_id: int, field: int,
                         parts: list = ()) -> None:
        payload = b"".join(
            p if isinstance(p, (bytes, bytearray)) else bytes(p)
            for p in parts)
        self.writer.write(struct.pack("<III", len(payload) + 8,
                                      req_id, field) + payload)
        await self.writer.drain()

    async def recv_frame(self) -> tuple[int, int, list]:
        (n,) = struct.unpack("<I", await self.reader.readexactly(4))
        body = await self.reader.readexactly(n)
        req_id, field = struct.unpack_from("<II", body)
        return req_id, field, [memoryview(body)[8:]]

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass  # lint: ignore[GL05] transport close is best-effort


async def _plain_client_handshake(reader, writer, netid: bytes, privkey
                                  ) -> tuple[bytes, PlainChannel]:
    """Initiator side of the no-crypto fallback: three messages so BOTH
    directions prove LIVE knowledge of the cluster secret (each side's
    final MAC covers the other side's fresh nonce — a recorded
    handshake replays into neither role); identity is the
    HashIdentityKey public id. Only reachable when BOTH ends lack the
    wheel — the distinct PLAIN_MAGIC makes mixed pairs fail closed."""
    import os as _os

    pub = privkey.public_key().public_bytes_raw()
    nonce = _os.urandom(16)
    hello = PLAIN_MAGIC + pub + nonce
    writer.write(hello + _hmac(netid, b"hello-plain", hello))
    await writer.drain()

    srv = await reader.readexactly(len(PLAIN_MAGIC) + 32 + 16 + 32)
    if srv[: len(PLAIN_MAGIC)] != PLAIN_MAGIC:
        raise HandshakeError(
            "protocol mismatch (peer has crypto transport; this node "
            "lacks the `cryptography` wheel)")
    off = len(PLAIN_MAGIC)
    srv_pub = srv[off : off + 32]
    head = srv[: off + 48]
    srv_mac = srv[off + 48 : off + 80]
    # server's MAC covers OUR nonce (inside hello): server is live
    if not hmac_mod.compare_digest(
            srv_mac, _hmac(netid, b"srv-plain", hello, head)):
        raise HandshakeError("peer does not know the cluster secret")
    # confirm over the server's fresh nonce (inside head): we are live
    writer.write(_hmac(netid, b"cli-plain", hello, head))
    await writer.drain()
    return srv_pub, PlainChannel(reader, writer)


async def _plain_server_handshake(reader, writer, netid: bytes, privkey
                                  ) -> tuple[bytes, PlainChannel]:
    """Acceptor side of the no-crypto fallback. The hello MAC alone is
    replayable (it covers only client-chosen bytes), so the channel is
    granted ONLY after the client's confirm MAC over our fresh nonce —
    a recorded handshake cannot be replayed into a usable channel."""
    import os as _os

    hello = await reader.readexactly(len(PLAIN_MAGIC) + 32 + 16)
    mac = await reader.readexactly(32)
    if hello[: len(PLAIN_MAGIC)] != PLAIN_MAGIC:
        raise HandshakeError(
            "protocol mismatch (peer has crypto transport; this node "
            "lacks the `cryptography` wheel)")
    if not hmac_mod.compare_digest(mac,
                                   _hmac(netid, b"hello-plain", hello)):
        raise HandshakeError("peer does not know the cluster secret")
    cli_pub = hello[len(PLAIN_MAGIC) : len(PLAIN_MAGIC) + 32]
    pub = privkey.public_key().public_bytes_raw()
    head = PLAIN_MAGIC + pub + _os.urandom(16)
    writer.write(head + _hmac(netid, b"srv-plain", hello, head))
    await writer.drain()
    confirm = await reader.readexactly(32)
    if not hmac_mod.compare_digest(
            confirm, _hmac(netid, b"cli-plain", hello, head)):
        raise HandshakeError("peer failed the liveness confirm")
    return cli_pub, PlainChannel(reader, writer)


async def client_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    netid: bytes,
    privkey: Ed25519PrivateKey,
) -> tuple[bytes, "SecureChannel"]:
    """Initiator side. Returns (peer node id, channel)."""
    if not HAVE_CRYPTO:
        return await _plain_client_handshake(reader, writer, netid,
                                             privkey)
    pub = privkey.public_key().public_bytes_raw()
    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes_raw()
    hello = MAGIC + pub + eph_pub
    writer.write(hello + _hmac(netid, b"hello", hello))
    await writer.drain()

    srv = await reader.readexactly(len(MAGIC) + 32 + 32 + 32 + 64)
    if srv[: len(MAGIC)] != MAGIC:
        raise HandshakeError("protocol version mismatch")
    off = len(MAGIC)
    srv_pub, srv_eph, srv_mac = srv[off : off + 32], srv[off + 32 : off + 64], srv[off + 64 : off + 96]
    srv_sig = srv[off + 96 :]
    transcript = b"srv" + hello + srv[: off + 64]
    if not hmac_mod.compare_digest(srv_mac, _hmac(netid, transcript)):
        raise HandshakeError("peer does not know the cluster secret")
    Ed25519PublicKey.from_public_bytes(srv_pub).verify(srv_sig, transcript)

    sig = privkey.sign(b"cli" + transcript)
    writer.write(sig)
    await writer.drain()

    shared = eph.exchange(X25519PublicKey.from_public_bytes(srv_eph))
    secret = _hkdf(shared, b"garage-tpu-channel" + eph_pub + srv_eph)
    chan = SecureChannel(reader, writer, send_key=_hkdf(secret, b"c2s"), recv_key=_hkdf(secret, b"s2c"))
    return srv_pub, chan


async def server_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    netid: bytes,
    privkey: Ed25519PrivateKey,
) -> tuple[bytes, "SecureChannel"]:
    """Acceptor side. Returns (peer node id, channel)."""
    if not HAVE_CRYPTO:
        return await _plain_server_handshake(reader, writer, netid,
                                             privkey)
    hello = await reader.readexactly(len(MAGIC) + 32 + 32)
    mac = await reader.readexactly(32)
    if hello[: len(MAGIC)] != MAGIC:
        raise HandshakeError("protocol version mismatch")
    if not hmac_mod.compare_digest(mac, _hmac(netid, b"hello", hello)):
        raise HandshakeError("peer does not know the cluster secret")
    off = len(MAGIC)
    cli_pub, cli_eph = hello[off : off + 32], hello[off + 32 : off + 64]

    pub = privkey.public_key().public_bytes_raw()
    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes_raw()
    head = MAGIC + pub + eph_pub
    transcript = b"srv" + hello + head
    srv_mac = _hmac(netid, transcript)
    sig = privkey.sign(transcript)
    writer.write(head + srv_mac + sig)
    await writer.drain()

    cli_sig = await reader.readexactly(64)
    Ed25519PublicKey.from_public_bytes(cli_pub).verify(cli_sig, b"cli" + transcript)

    shared = eph.exchange(X25519PublicKey.from_public_bytes(cli_eph))
    secret = _hkdf(shared, b"garage-tpu-channel" + cli_eph + eph_pub)
    chan = SecureChannel(reader, writer, send_key=_hkdf(secret, b"s2c"), recv_key=_hkdf(secret, b"c2s"))
    return cli_pub, chan


class SecureChannel:
    """ChaCha20-Poly1305 record layer: [u32 ct_len][ct]; counter nonces.
    Frames are [u32 req_id][u32 field][payload] inside the record."""

    max_chunk = MAX_CHUNK

    def __init__(self, reader, writer, send_key: bytes, recv_key: bytes):
        self.reader = reader
        self.writer = writer
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_ctr = 0
        self._recv_ctr = 0

    def _nonce(self, ctr: int) -> bytes:
        return ctr.to_bytes(12, "little")

    async def send_frame(self, req_id: int, field: int,
                         parts: list = ()) -> None:
        pt = struct.pack("<II", req_id, field) + b"".join(
            p if isinstance(p, (bytes, bytearray)) else bytes(p)
            for p in parts)
        ct = self._send.encrypt(self._nonce(self._send_ctr), pt, None)
        self._send_ctr += 1
        self.writer.write(struct.pack("<I", len(ct)) + ct)
        await self.writer.drain()

    async def recv_frame(self) -> tuple[int, int, list]:
        (n,) = struct.unpack("<I", await self.reader.readexactly(4))
        ct = await self.reader.readexactly(n)
        pt = self._recv.decrypt(self._nonce(self._recv_ctr), ct, None)
        self._recv_ctr += 1
        req_id, field = struct.unpack_from("<II", pt)
        return req_id, field, [memoryview(pt)[8:]]

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass  # lint: ignore[GL05] transport close is best-effort


class _SendItem:
    """One in-flight outgoing message: body section then optional stream.

    Stream chunks are prefetched by a side task so a stalled stream
    source never parks the connection's single send loop (the reference
    gets this for free from its polled scheduler, src/net/send.rs).
    """

    __slots__ = (
        "req_id", "prio", "body", "buf_idx", "pos", "body_done", "stream",
        "is_error", "done", "kind", "next_chunk", "chunk_state", "prefetch",
        "window", "order_clock",
    )

    def __init__(self, req_id, prio, body, stream, is_error, kind="msg"):
        self.req_id = req_id
        self.prio = prio
        self.body = body  # list of buffers (scatter-gather)
        self.buf_idx = 0
        self.pos = 0
        self.body_done = False
        self.stream = stream
        self.is_error = is_error
        self.kind = kind  # "msg" | "cancel" | "credit"
        self.next_chunk: Optional[bytes] = None
        self.chunk_state = "none"  # none|fetching|ready|eof|error
        self.prefetch: Optional[asyncio.Task] = None
        self.window = STREAM_WINDOW
        self.order_clock = 0
        self.done = asyncio.get_event_loop().create_future()


class _RecvState:
    """Reassembly of one incoming message."""

    __slots__ = ("parts", "stream", "is_error", "credited")

    def __init__(self):
        self.parts: list = []
        self.stream: Optional[ByteStream] = None
        self.is_error = False
        self.credited = 0


class Conn:
    """One duplex multiplexed connection to a peer.

    Either side can issue requests; the initiator uses even request ids,
    the acceptor odd (the reference instead opens two connections,
    src/net/netapp.rs server_conns/client_conns — one duplex socket is
    the asyncio-native shape).
    """

    def __init__(
        self,
        peer_id: bytes,
        channel: SecureChannel,
        handler: Callable[..., Awaitable],
        initiator: bool,
        local_id: bytes = b"",
    ):
        self.peer_id = peer_id
        # our own node id, for chaos scoping only (partition_zone needs
        # BOTH endpoints of a frame; defaulted empty for bare tests)
        self.local_id = local_id
        self.chan = channel
        self.handler = handler  # (peer_id, path, prio, order, payload, stream)
        self._next_id = 2 if initiator else 3
        self._send_items: dict[int, _SendItem] = {}
        self._ctl_items: list[_SendItem] = []
        self._send_wakeup = asyncio.Event()
        self._send_clock = 0
        self._recv_states: dict[int, _RecvState] = {}
        self._reply_waiters: dict[int, asyncio.Future] = {}
        self._handler_tasks: dict[int, asyncio.Task] = {}
        self._tasks: list[asyncio.Task] = []
        self.closed = asyncio.get_event_loop().create_future()

    def start(self) -> None:
        self._tasks = [
            asyncio.create_task(self._send_loop()),
            asyncio.create_task(self._recv_loop()),
        ]
        for t in self._tasks:
            # supervised by close(): not leaks for the sanitizer's
            # loop-teardown check
            t._garage_background = True

    # ---- outgoing ------------------------------------------------------

    def _alloc_id(self) -> int:
        i = self._next_id
        self._next_id += 2
        return i

    def enqueue(
        self,
        req_id: int,
        prio: int,
        body: list,
        stream: Optional[ByteStream] = None,
        is_error: bool = False,
    ) -> _SendItem:
        item = _SendItem(req_id, prio, body, stream, is_error)
        self._send_items[req_id] = item
        self._send_wakeup.set()
        return item

    def _enqueue_ctl(self, kind: str, req_id: int, payload: bytes = b"") -> None:
        item = _SendItem(req_id, 0, [payload], None, False, kind=kind)
        self._ctl_items.append(item)
        self._send_wakeup.set()

    async def call(
        self,
        path: str,
        payload,
        prio: int = PRIO_HIGH,
        stream: Optional[ByteStream] = None,
        timeout: Optional[float] = None,
        order: Optional[tuple[int, int]] = None,
    ):
        """Send a request, await (payload, reply_stream)."""
        from ..utils.tracing import current_trace_id

        if self.closed.done():
            # a dead conn stays in netapp.conns until the done-callback
            # runs (next loop tick); a call landing in that window would
            # enqueue into a conn whose loops are gone and wait out its
            # full timeout instead of failing fast
            raise RpcError("connection closed")
        req_id = self._alloc_id()
        rest, blob_key, blob = split_blob(payload)
        body = pack_body([path, prio, stream is not None, order, rest,
                          blob_key, current_trace_id()], blob)
        fut = asyncio.get_event_loop().create_future()
        self._reply_waiters[req_id] = fut
        self.enqueue(req_id, prio, body, stream)
        try:
            return await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            self._abort_send(req_id)
            self._enqueue_ctl("cancel", req_id)
            raise
        finally:
            self._reply_waiters.pop(req_id, None)

    def _abort_send(self, req_id: int) -> None:
        item = self._send_items.pop(req_id, None)
        if item is not None and item.prefetch is not None:
            item.prefetch.cancel()

    # ---- send scheduler ------------------------------------------------

    async def _send_loop(self) -> None:
        try:
            while True:
                item = self._pick_item()
                if item is None:
                    # lint: ignore[GL12] wakeup handshake: clear, re-check _pick_item, then wait — a set() racing the clear is caught by the re-check; a spurious wake costs one loop turn
                    self._send_wakeup.clear()
                    # re-check: a prefetch may have completed in between
                    if self._pick_item() is None:
                        await self._send_wakeup.wait()
                    continue
                await self._send_one_chunk(item)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._fail(e)

    def _pick_item(self) -> Optional[_SendItem]:
        """Control frames first; then highest priority, round-robin within
        the level by least-recently-sent (ref: src/net/send.rs:48-60)."""
        if self._ctl_items:
            return self._ctl_items[0]
        best: Optional[_SendItem] = None
        for item in self._send_items.values():
            if not self._sendable(item):
                continue
            if (
                best is None
                or item.prio < best.prio
                or (item.prio == best.prio and item.order_clock < best.order_clock)
            ):
                best = item
        return best

    def _sendable(self, item: _SendItem) -> bool:
        if not item.body_done:
            return True
        if item.stream is None:
            return True  # finished body, will finalize
        if item.chunk_state == "fetching":
            return False
        if item.chunk_state in ("ready", "eof", "error"):
            return item.window > 0 or item.chunk_state in ("eof", "error")
        # chunk_state == "none": start a prefetch, not sendable yet
        self._start_prefetch(item)
        return False

    def _start_prefetch(self, item: _SendItem) -> None:
        item.chunk_state = "fetching"

        async def fetch():
            try:
                chunk = await item.stream.read_chunk(MAX_CHUNK)
                item.next_chunk = chunk
                item.chunk_state = "eof" if not chunk else "ready"
            except Exception:
                item.chunk_state = "error"
            self._send_wakeup.set()

        item.prefetch = asyncio.create_task(fetch())

    @staticmethod
    def _next_body_parts(item: _SendItem, max_chunk: int) -> tuple[list, int]:
        """Advance the body cursor by up to max_chunk bytes; returns the
        scatter list (memoryview slices — no copies) and its length."""
        parts: list = []
        n = 0
        while item.buf_idx < len(item.body) and n < max_chunk:
            buf = item.body[item.buf_idx]
            blen = len(buf)
            take = min(blen - item.pos, max_chunk - n)
            if take == blen and item.pos == 0:
                parts.append(buf)
            else:
                parts.append(memoryview(buf)[item.pos:item.pos + take])
            item.pos += take
            n += take
            if item.pos >= blen:
                item.buf_idx += 1
                item.pos = 0
        return parts, n

    async def _chaos_net(self, direction: str, nbytes: int) -> bool:
        """Chaos seam (net): delay/drop/disconnect/slow-drip scoped by
        the remote peer id. True = proceed, False = drop the frame.
        No-op fast path when chaos is disarmed."""
        if _chaos.ACTIVE is None:
            return True
        return await _chaos.ACTIVE.net_frame(direction, self.local_id,
                                             self.peer_id, nbytes)

    async def _send_one_chunk(self, item: _SendItem) -> None:
        if item.kind == "cancel":
            self._ctl_items.remove(item)
            await self.chan.send_frame(item.req_id, CANCEL)
            return
        if item.kind == "credit":
            self._ctl_items.remove(item)
            await self.chan.send_frame(item.req_id, CREDIT, item.body)
            return
        self._send_clock += 1
        item.order_clock = self._send_clock
        flags_base = F_ERROR if item.is_error else 0
        if not item.body_done:
            parts, n = self._next_body_parts(item, self.chan.max_chunk)
            item.body_done = item.buf_idx >= len(item.body)
            flags = flags_base | (0 if item.body_done else F_CONT)
            if await self._chaos_net("send", n):
                await self.chan.send_frame(item.req_id, flags | n, parts)
            if item.body_done and item.stream is None:
                self._finish_item(item)
            return
        # stream section
        if item.chunk_state == "error":
            await self.chan.send_frame(item.req_id, F_STREAM | F_ERROR)
            self._finish_item(item)
            return
        if item.chunk_state == "eof":
            await self.chan.send_frame(item.req_id, F_STREAM)
            self._finish_item(item)
            return
        assert item.chunk_state == "ready"
        chunk = item.next_chunk or b""
        send_now = chunk[: max(0, item.window)]
        rest = chunk[len(send_now) :]
        if rest:
            item.next_chunk = rest  # window-limited; stays ready
        else:
            item.next_chunk = None
            item.chunk_state = "none"
        item.window -= len(send_now)
        if await self._chaos_net("send", len(send_now)):
            await self.chan.send_frame(
                item.req_id, F_STREAM | F_CONT | len(send_now), [send_now])

    def _finish_item(self, item: _SendItem) -> None:
        self._send_items.pop(item.req_id, None)
        if not item.done.done():
            item.done.set_result(None)

    # ---- incoming ------------------------------------------------------

    async def _recv_loop(self) -> None:
        try:
            while True:
                req_id, field, parts = await self.chan.recv_frame()
                if _chaos.ACTIVE is not None and not await self._chaos_net(
                        "recv", sum(len(p) for p in parts)):
                    continue  # frame lost on the (simulated) wire
                if field == CANCEL:
                    self._handle_cancel(req_id)
                elif field == CREDIT:
                    self._handle_credit(
                        req_id, bytes(parts[0][:4]) if parts else b"")
                else:
                    self._handle_chunk(req_id, field, parts)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._fail(e)

    def _handle_cancel(self, req_id: int) -> None:
        task = self._handler_tasks.pop(req_id, None)
        if task is not None:
            task.cancel()
        self._abort_send(req_id)
        st = self._recv_states.pop(req_id, None)
        if st is not None and st.stream is not None:
            st.stream.push_error(RpcError("cancelled by peer"))

    def _handle_credit(self, req_id: int, payload: bytes) -> None:
        item = self._send_items.get(req_id)
        if item is not None and len(payload) >= 4:
            item.window += struct.unpack("<I", payload[:4])[0]
            self._send_wakeup.set()

    def _grant_credit(self, req_id: int, stream: ByteStream) -> None:
        """Wire consumer progress to CREDIT grants back to the sender."""
        state = {"pending": 0}

        def consumed(n: int) -> None:
            state["pending"] += n
            if state["pending"] >= CREDIT_BATCH:
                grant, state["pending"] = state["pending"], 0
                self._enqueue_ctl("credit", req_id, struct.pack("<I", grant))

        stream.on_consume = consumed

    def _handle_chunk(self, req_id: int, field: int, parts: list) -> None:
        mine = (req_id % 2 == 0) == (self._next_id % 2 == 0)
        st = self._recv_states.get(req_id)
        if st is None:
            st = self._recv_states[req_id] = _RecvState()
        if field & F_STREAM:
            if st.stream is None:
                st.stream = ByteStream()
            if field & F_ERROR:
                st.stream.push_error(RpcError("peer stream failed"))
                self._recv_states.pop(req_id, None)
            elif field & F_CONT:
                for p in parts:
                    if len(p):
                        st.stream.push(p if isinstance(p, bytes)
                                       else bytes(p))
            else:
                for p in parts:
                    if len(p):
                        st.stream.push(p if isinstance(p, bytes)
                                       else bytes(p))
                st.stream.push_eof()
                self._recv_states.pop(req_id, None)
            return
        st.parts.extend(parts)
        st.is_error = st.is_error or bool(field & F_ERROR)
        if field & F_CONT:
            return
        try:
            header, blob = parse_body(st.parts)
        except Exception:
            # fragment of a cancelled request whose state we dropped —
            # drop it; the request id is dead
            self._recv_states.pop(req_id, None)
            return
        if mine:
            self._deliver_reply(req_id, st, header, blob)
        else:
            self._dispatch_request(req_id, st, header, blob)

    @staticmethod
    def _expect_stream(header) -> bool:
        # reply header: [ok, payload, has_stream, blob_key]
        return bool(header[2]) if isinstance(header, list) and len(header) >= 3 else False

    def _deliver_reply(self, req_id: int, st: _RecvState, header, blob) -> None:
        # reply header: [ok, payload, has_stream, blob_key]
        fut = self._reply_waiters.pop(req_id, None)
        has_stream = self._expect_stream(header)
        if has_stream and st.stream is None:
            st.stream = ByteStream()
        if not has_stream:
            self._recv_states.pop(req_id, None)
        if fut is None or fut.done():
            if st.stream:
                st.stream.discard()
            return
        if st.is_error or (isinstance(header, list) and not header[0]):
            msg = header[1] if isinstance(header, list) else "remote error"
            fut.set_exception(RpcError(str(msg)))
        else:
            if st.stream is not None:
                self._grant_credit(req_id, st.stream)
            bkey = header[3] if len(header) > 3 else None
            fut.set_result((_attach_blob(header[1], bkey, blob), st.stream))

    def _dispatch_request(self, req_id: int, st: _RecvState, header, blob) -> None:
        # request header:
        # [path, prio, has_stream, order, payload, blob_key, trace_id]
        path, prio, has_stream, order, payload, bkey = header[:6]
        trace_id = header[6] if len(header) > 6 else None
        payload = _attach_blob(payload, bkey, blob)
        if has_stream and st.stream is None:
            st.stream = ByteStream()
        if st.stream is not None:
            self._grant_credit(req_id, st.stream)
        if not has_stream:
            self._recv_states.pop(req_id, None)
        task = asyncio.create_task(
            self._run_handler(req_id, path, prio, order, payload, st.stream,
                              trace_id)
        )
        # supervised: tracked in _handler_tasks, cancelled by close()
        task._garage_background = True
        self._handler_tasks[req_id] = task
        task.add_done_callback(lambda t: self._handler_tasks.pop(req_id, None))

    async def _run_handler(self, req_id, path, prio, order, payload, stream,
                           trace_id=None) -> None:
        try:
            if trace_id is not None:
                from ..utils.tracing import set_remote_context

                set_remote_context(trace_id)
            result, reply_stream = await self.handler(
                self.peer_id, path, prio, order, payload, stream
            )
            rest, blob_key, blob = split_blob(result)
            body = pack_body([True, rest, reply_stream is not None,
                              blob_key], blob)
            self.enqueue(req_id, prio, body, reply_stream)
        except asyncio.CancelledError:
            pass
        except Exception as e:
            log.debug("handler error on %s: %s", path, e, exc_info=True)
            self.enqueue(req_id, prio, pack_body(
                [False, f"{type(e).__name__}: {e}", False, None], None))

    # ---- lifecycle -----------------------------------------------------

    def _fail(self, exc: Exception) -> None:
        for fut in self._reply_waiters.values():
            if not fut.done():
                fut.set_exception(RpcError(f"connection lost: {exc}"))
        self._reply_waiters.clear()
        for st in self._recv_states.values():
            if st.stream:
                st.stream.push_error(RpcError("connection lost"))
        self._recv_states.clear()
        for item in self._send_items.values():
            if item.prefetch is not None:
                item.prefetch.cancel()
        self._send_items.clear()
        for t in self._handler_tasks.values():
            t.cancel()
        if not self.closed.done():
            self.closed.set_result(exc)
        self.chan.close()

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._fail(RpcError("closed"))
