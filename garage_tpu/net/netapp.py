"""NetApp: node identity, connection registry, listen/connect, dispatch.

Ref parity: src/net/netapp.rs:65-470. Node identity is an ed25519 public
key (NodeID); the cluster secret `netid` gates the handshake; typed
endpoints are registered by path. Divergences: one duplex connection per
peer pair instead of separate client/server connections, and loopback
calls short-circuit in-process without serialization.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import \
        Ed25519PrivateKey
except ModuleNotFoundError:
    Ed25519PrivateKey = None

from ..utils.background import spawn
from ..utils.error import RpcError
from .conn import Conn, SecureChannel, client_handshake, server_handshake
from .endpoint import Endpoint
from .stream import ByteStream

log = logging.getLogger("garage_tpu.net")


class HashIdentityKey:
    """Stand-in node key when the `cryptography` wheel is absent.

    The in-process LocalNetwork transport never signs anything — a node
    key there is pure identity, so 32 random private bytes with a
    blake2b-derived "public" id give the same uniqueness and
    persistence semantics. Same raw-bytes (de)serialization surface as
    Ed25519PrivateKey so load_or_gen_node_key round-trips either kind
    (though a key file is only portable between same-capability
    builds). TCP handshakes refuse separately (conn.py HAVE_CRYPTO)."""

    def __init__(self, raw: bytes):
        self._raw = raw
        import hashlib

        self._pub = hashlib.blake2b(b"gt-node-id" + raw,
                                    digest_size=32).digest()

    @classmethod
    def generate(cls) -> "HashIdentityKey":
        import os

        return cls(os.urandom(32))

    def public_key(self) -> "HashIdentityKey":
        return self  # duck-typed: caller only wants public_bytes_raw()

    def public_bytes_raw(self) -> bytes:
        return self._pub

    def private_bytes_raw(self) -> bytes:
        return self._raw

    def sign(self, _msg: bytes) -> bytes:
        raise RpcError("node key cannot sign: `cryptography` wheel "
                       "not installed")


def gen_node_key():
    if Ed25519PrivateKey is None:
        return HashIdentityKey.generate()
    return Ed25519PrivateKey.generate()


def node_key_from_bytes(raw: bytes):
    if Ed25519PrivateKey is None:
        return HashIdentityKey(raw)
    return Ed25519PrivateKey.from_private_bytes(raw)


def node_key_to_bytes(key) -> bytes:
    return key.private_bytes_raw()


class _OrderedDispatch:
    """Runs handlers carrying the same OrderTag stream in seq order
    (ref: src/net/message.rs:62-88). Cancelled/failed seqs are tombstoned
    via done() so later seqs never stall behind a seq that will never
    complete."""

    def __init__(self):
        self._streams: dict[tuple[bytes, int], dict] = {}

    async def gate(self, peer: bytes, stream_id: int, seq: int):
        key = (peer, stream_id)
        st = self._streams.get(key)
        if st is None:
            st = self._streams[key] = {
                "next": 0, "finished": set(), "ev": asyncio.Event(),
                "t": time.monotonic(), "waiters": 0,
            }
        st["waiters"] += 1
        try:
            while st["next"] < seq:
                st["ev"].clear()
                await st["ev"].wait()
        finally:
            st["waiters"] -= 1
        st["t"] = time.monotonic()

    def done(self, peer: bytes, stream_id: int, seq: int):
        st = self._streams.get((peer, stream_id))
        if st is None:
            return
        st["finished"].add(seq)
        while st["next"] in st["finished"]:
            st["finished"].discard(st["next"])
            st["next"] += 1
        st["t"] = time.monotonic()
        st["ev"].set()

    def prune(self, max_age: float = 600.0):
        # never prune a stream someone is gated on — deleting the entry
        # would orphan the waiter's event and hang it forever
        cutoff = time.monotonic() - max_age
        for key in [
            k
            for k, v in self._streams.items()
            if v["t"] < cutoff and v["waiters"] == 0
        ]:
            del self._streams[key]


class NetApp:
    """Connection manager + endpoint dispatcher for one node."""

    def __init__(
        self,
        netid: bytes,
        privkey: Optional[Ed25519PrivateKey] = None,
        bind_addr: Optional[tuple[str, int]] = None,
        public_addr: Optional[tuple[str, int]] = None,
    ):
        self.netid = netid
        self.privkey = privkey or gen_node_key()
        self.id: bytes = self.privkey.public_key().public_bytes_raw()
        self.bind_addr = bind_addr
        self.public_addr = public_addr or bind_addr
        self.endpoints: dict[str, Endpoint] = {}
        self.conns: dict[bytes, Conn] = {}
        self.on_connected: list[Callable[[bytes, bool], None]] = []
        self.on_disconnected: list[Callable[[bytes], None]] = []
        self._server: Optional[asyncio.base_events.Server] = None
        self._ordered = _OrderedDispatch()
        self._connecting: dict[bytes, asyncio.Future] = {}
        self.local_net = None  # set by local.LocalNetwork.register

    # ---- endpoints -----------------------------------------------------

    def endpoint(self, path: str) -> Endpoint:
        ep = self.endpoints.get(path)
        if ep is None:
            ep = self.endpoints[path] = Endpoint(self, path)
        return ep

    # ---- listen / connect ---------------------------------------------

    async def listen(self) -> None:
        if self._server is not None:
            return  # already listening (idempotent for composition roots)
        assert self.bind_addr is not None, "no bind_addr configured"
        host, port = self.bind_addr
        # lint: ignore[GL12] listen() is called once from the composition root before any request task exists; the None check above is an idempotence guard, not concurrency control
        self._server = await asyncio.start_server(self._accept, host, port)
        if port == 0:  # test convenience: recover the kernel-chosen port
            port = self._server.sockets[0].getsockname()[1]
            # lint: ignore[GL12] same single-task startup window as _server above
            self.bind_addr = (host, port)
            if self.public_addr is None or self.public_addr[1] == 0:
                self.public_addr = (host, port)
        log.info("listening on %s:%d", host, port)

    async def _accept(self, reader, writer) -> None:
        try:
            peer_id, chan = await asyncio.wait_for(
                server_handshake(reader, writer, self.netid, self.privkey), 10.0
            )
        except Exception as e:
            log.debug("handshake failed from %s: %s", writer.get_extra_info("peername"), e)
            writer.close()
            return
        self._register(peer_id, chan, initiator=False)

    async def try_connect(self, addr: tuple[str, int], expected_id: Optional[bytes] = None) -> bytes:
        """Connect to a peer at addr; returns its node id."""
        if self.local_net is not None:
            return await self.local_net.connect_from(self, addr, expected_id)
        if expected_id is not None:
            if expected_id == self.id:
                return self.id
            existing = self.conns.get(expected_id)
            if existing is not None:
                return expected_id
            inflight = self._connecting.get(expected_id)
            if inflight is not None:
                return await asyncio.shield(inflight)
            self._connecting[expected_id] = asyncio.get_event_loop().create_future()
        try:
            reader, writer = await asyncio.wait_for(asyncio.open_connection(*addr), 10.0)
            peer_id, chan = await asyncio.wait_for(
                client_handshake(reader, writer, self.netid, self.privkey), 10.0
            )
            if expected_id is not None and peer_id != expected_id:
                chan.close()
                raise RpcError("peer identity mismatch")
            self._register(peer_id, chan, initiator=True)
            if expected_id is not None:
                fut = self._connecting.pop(expected_id, None)
                if fut and not fut.done():
                    fut.set_result(peer_id)
            return peer_id
        except BaseException as e:
            if expected_id is not None:
                fut = self._connecting.pop(expected_id, None)
                if fut and not fut.done():
                    fut.set_exception(e if isinstance(e, Exception) else RpcError(str(e)))
                    # consume so "exception never retrieved" isn't logged
                    fut.exception()
            raise

    def _register(self, peer_id: bytes, chan, initiator: bool) -> None:
        old = self.conns.get(peer_id)
        if old is not None and old.closed.done():
            # a dead conn lingers in the map until its done-callback
            # tick; it must never win a tiebreak against a fresh channel
            del self.conns[peer_id]
            old = None
        if old is not None:
            # simultaneous-connect tiebreak: keep the connection whose
            # initiator is the lexicographically smaller node id
            we_should_initiate = self.id < peer_id
            if old_is_initiated(old) == we_should_initiate != initiator:
                chan.close()
                return
            spawn(old.close(), "netapp-replace-conn-close")
        conn = Conn(peer_id, chan, self._handle_request, initiator,
                    local_id=self.id)
        self.conns[peer_id] = conn
        conn.start()
        conn.closed.add_done_callback(lambda _: self._on_conn_closed(peer_id, conn))
        for cb in self.on_connected:
            try:
                cb(peer_id, not initiator)
            except Exception:
                log.exception("on_connected callback failed")

    def _on_conn_closed(self, peer_id: bytes, conn: Conn) -> None:
        if self.conns.get(peer_id) is conn:
            del self.conns[peer_id]
            for cb in self.on_disconnected:
                try:
                    cb(peer_id)
                except Exception:
                    log.exception("on_disconnected callback failed")

    def is_connected(self, node: bytes) -> bool:
        return node == self.id or node in self.conns

    # ---- calls ---------------------------------------------------------

    async def call(
        self,
        node: bytes,
        path: str,
        payload,
        prio: int,
        stream: Optional[ByteStream] = None,
        timeout: Optional[float] = None,
        order: Optional[tuple[int, int]] = None,
    ):
        if node == self.id:
            result, reply_stream = await self._handle_request(
                self.id, path, prio, order, payload, stream
            )
            return result, reply_stream
        conn = self.conns.get(node)
        if conn is None:
            raise RpcError(f"not connected to {node[:4].hex()}")
        return await conn.call(path, payload, prio, stream=stream, timeout=timeout, order=order)

    async def _handle_request(self, from_node, path, prio, order, payload, stream):
        ep = self.endpoints.get(path)
        if ep is None:
            raise RpcError(f"no such endpoint: {path}")
        if order is not None:
            sid, seq = order
            try:
                await self._ordered.gate(from_node, sid, seq)
                return await ep.handle(from_node, payload, stream)
            finally:
                # also on cancellation while gated: tombstone this seq so
                # later seqs of the stream don't stall forever
                self._ordered.done(from_node, sid, seq)
        return await ep.handle(from_node, payload, stream)

    # ---- lifecycle -----------------------------------------------------

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
        # pop-then-close (GL12): iterating a snapshot and then
        # clear()ing raced _register() — a connection accepted while an
        # earlier close() awaited survived the snapshot and was then
        # dropped from the map WITHOUT being closed (leaked socket, the
        # peer kept a half-open channel). Popping drains whatever is
        # present at each step, including late registrations.
        while self.conns:
            _, conn = self.conns.popitem()
            await conn.close()


def old_is_initiated(conn: Conn) -> bool:
    return conn._next_id % 2 == 0
