"""Typed endpoints: path-keyed handler registry.

Ref parity: src/net/endpoint.rs:18-104 — endpoints are named by path
strings like "garage_table/table.rs/Rpc:object"; handlers receive the
decoded payload plus the sender's node id, and may consume/produce byte
streams.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Optional

from ..chaos import injector as _chaos
from ..utils.error import RpcError
from .stream import ByteStream

# handler(from_node: bytes, payload, stream: Optional[ByteStream])
#   -> payload | (payload, Optional[ByteStream])
Handler = Callable[..., Awaitable]


class Endpoint:
    """One named RPC endpoint on a NetApp."""

    def __init__(self, netapp, path: str):
        self.netapp = netapp
        self.path = path
        self._handler: Optional[Handler] = None

    def set_handler(self, handler: Handler) -> "Endpoint":
        self._handler = handler
        return self

    async def handle(self, from_node: bytes, payload, stream: Optional[ByteStream]):
        if self._handler is None:
            raise RpcError(f"no handler for {self.path}")
        result = await self._handler(from_node, payload, stream)
        if isinstance(result, tuple) and len(result) == 2 and (
            result[1] is None or isinstance(result[1], ByteStream)
        ):
            return result
        return result, None

    async def call(
        self,
        node: bytes,
        payload,
        prio: int,
        stream: Optional[ByteStream] = None,
        timeout: Optional[float] = None,
        order: Optional[tuple[int, int]] = None,
    ):
        """Call this endpoint on `node` (loopback if node is ourself).
        Returns (payload, reply_stream|None)."""
        from ..utils.metrics import registry
        from ..utils.tracing import span

        # bg label partitions foreground (interactive) from background
        # (resync/scrub bulk) series so the qos governor can sample
        # foreground latency without chasing its own repair traffic
        from .message import PRIO_BACKGROUND

        with registry().timer("rpc_request_duration_seconds",
                              endpoint=self.path,
                              bg="1" if prio >= PRIO_BACKGROUND else "0"):
            try:
                # chaos seam (rpc): error/hang injection scoped by
                # endpoint path + target node; one attribute load and a
                # None check when disarmed
                if _chaos.ACTIVE is not None:
                    await _chaos.ACTIVE.rpc_call(self.path, node, timeout)
                async with span("rpc.call", endpoint=self.path,
                                node=node[:4].hex()):
                    return await self.netapp.call(
                        node, self.path, payload, prio, stream=stream,
                        timeout=timeout, order=order
                    )
            except Exception:
                registry().inc("rpc_request_errors", endpoint=self.path)
                raise
