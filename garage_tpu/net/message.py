"""Request priorities, order tags, and payload encoding.

Ref parity: src/net/message.rs:15-88 (RequestPriority bits, OrderTag) and
the msgpack payload convention used throughout the reference. Payloads
here are plain msgpack-encodable Python values (dicts/lists/bytes/ints);
typed schemas live at the endpoint layer.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

import msgpack

# Priority byte: lower value = more urgent. Bit 0 picks primary/secondary
# send queue within a level (ref: src/net/message.rs:49-58).
PRIO_HIGH = 0x20  # pings, membership gossip — must beat bulk data
PRIO_NORMAL = 0x40  # interactive metadata RPC
PRIO_BACKGROUND = 0x80  # resync/sync bulk transfers
PRIO_PRIMARY = 0x00
PRIO_SECONDARY = 0x01


@dataclass(frozen=True)
class OrderTag:
    """Orders sub-streams within one logical transfer: messages with the
    same `stream` id are delivered in `seq` order even though they travel
    as independent requests (ref: src/net/message.rs:62-88). Used by the
    GET path to stream blocks of one object in order."""

    stream: int
    seq: int

    _counter = itertools.count(1)

    @classmethod
    def stream_id(cls) -> int:
        return next(cls._counter)


def pack(value) -> bytes:
    return msgpack.packb(value, use_bin_type=True)


def unpack(data: bytes):
    return msgpack.unpackb(data, raw=False, strict_map_key=False)
